"""Speculative decoding: self-draft proposers + the traced window verifier.

The decode engine's exact paths pay one full model step per emitted
token. Speculative decoding multiplies tokens-per-step without changing
the emitted stream: a cheap *drafter* proposes ``k`` candidate tokens
per slot, the target model scores all ``k + 1`` window positions in ONE
batched forward, and a traced accept/reject pass keeps exactly the
prefix of drafts the target itself would have emitted. This module
holds both halves of that split:

* **Host side** — :func:`ngram_propose` / :class:`NGramDrafter`, a
  prompt-lookup self-drafter over each request's own token history
  (prompt + everything emitted so far). No second model, no extra
  checkpoint, no device work: the drafter runs on tokens the host
  already holds, so proposing is free of device syncs by construction.
  ``make_drafter`` also accepts any callable ``(context, k) -> tokens``
  — the ``draft_model=`` hook for a real small model later — and
  :func:`plan_window` turns a slot's host state (prompt remainder, last
  token, draft proposals) into the window the device program consumes.

* **Device side** — :func:`verify_window`, the traced accept/reject
  mask over one slot's window logits. Acceptance is *token-matching*:
  position ``i``'s draft is accepted iff it equals the token the target
  would have emitted at position ``i`` under the request's own sampling
  chain (greedy argmax at temperature 0, the seeded categorical draw
  otherwise). That is deliberately stricter than classic lenient
  rejection sampling: every emitted token IS the target chain's own
  next token, so the emitted stream is identical token-for-token to
  ``generate_legacy`` — greedy and sampled alike — and the per-request
  RNG contract (one key split per emitted token) is preserved exactly.
  The drafts only decide how many of those tokens land per step.

Window layout (shared by the dense and paged spec steps): for a slot
with ``p`` prompt tokens still replaying, window inputs are
``pending[:min(p, W)]`` followed by draft proposals; ``n_known`` =
``min(p - 1, W)`` positions have successors already known (pure replay,
no emission, no RNG), position ``n_known`` is the first emitting
position, and the chain dies at the first mismatch or emitted eos.
``n_known == W`` means the whole window is replay — valid KV, zero
emissions — so long prompt remainders also advance ``W`` tokens/step.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple, Union

DrafterFn = Callable[[Sequence[int], int], Sequence[int]]


def ngram_propose(
    context: Sequence[int],
    k: int,
    max_ngram: int = 3,
    min_ngram: int = 1,
) -> List[int]:
    """Prompt-lookup proposal: find the most recent earlier occurrence
    of the context's trailing n-gram (longest n first) and copy the
    ``k`` tokens that followed it. Returns up to ``k`` tokens — possibly
    fewer (the match sat near the end) or none (no repeat structure)."""
    if k <= 0:
        return []
    n_ctx = len(context)
    context = list(context)
    for n in range(min(max_ngram, n_ctx - 1), min_ngram - 1, -1):
        suffix = context[n_ctx - n:]
        # Most recent prior occurrence: scan right-to-left, excluding
        # the suffix's own position.
        for start in range(n_ctx - n - 1, -1, -1):
            if context[start:start + n] == suffix:
                follow = context[start + n:start + n + k]
                if follow:
                    return [int(t) for t in follow]
    return []


class NGramDrafter:
    """The default self-drafter: :func:`ngram_propose` with fixed n-gram
    bounds. Stateless and host-pure — safe to share across slots."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"[{min_ngram}, {max_ngram}]"
            )
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def __call__(self, context: Sequence[int], k: int) -> List[int]:
        return ngram_propose(
            context, k, max_ngram=self.max_ngram, min_ngram=self.min_ngram
        )


def make_drafter(spec: Union[str, DrafterFn, None]) -> Optional[DrafterFn]:
    """Resolve a drafter spec: ``"ngram"`` -> :class:`NGramDrafter`,
    a callable -> itself (the ``draft_model=`` hook: wrap a real draft
    model behind ``(context, k) -> tokens``), ``None`` -> no drafting
    (the spec step still runs, one guaranteed token per tick)."""
    if spec is None:
        return None
    if callable(spec):
        return spec
    if spec == "ngram":
        return NGramDrafter()
    raise ValueError(
        f"spec_draft must be 'ngram', a callable (context, k) -> tokens, "
        f"or None; got {spec!r}"
    )


def plan_window(
    pending: Sequence[int],
    last_token: int,
    width: int,
    max_emit: int,
    context: Sequence[int],
    drafter: Optional[DrafterFn],
    *,
    max_drafts: Optional[int] = None,
) -> Tuple[List[int], int, int]:
    """One slot's window inputs for a spec step (host side).

    Returns ``(tokens, n_known, n_proposed)``: ``width`` input tokens
    (prompt-replay prefix, then up to ``max_emit - 1`` draft proposals,
    then ``-1`` fill that can never match a real token), the count of
    positions whose successor is already known, and how many drafts
    were actually proposed (the accept-rate denominator).

    A window whose inputs are all pending prompt tokens is a
    **teacher-forced chunk**: ``n_known == width`` positions replay
    known successors, nothing emits, no RNG is consumed, and the slot's
    KV advances ``width`` tokens in one step — chunked prefill
    (docs/Serving.md "Chunked prefill") is nothing but a stream of
    these riding the ordinary spec step. ``max_drafts`` caps drafting
    independently of the window width: a chunked grid widens the window
    to ``prefill_chunk`` without widening the draft budget past
    ``spec_k``, so the tail chunk (replay shorter than the window)
    never over-drafts."""
    p = len(pending)
    if p > 0:
        take = min(p, width)
        tokens = [int(t) for t in list(pending)[:take]]
        n_known = min(p - 1, width)
    else:
        tokens = [int(last_token)]
        n_known = 0
    draft_room = width - 1 - n_known
    n_drafts = max(0, min(draft_room, max_emit - 1))
    if max_drafts is not None:
        n_drafts = min(n_drafts, max(0, int(max_drafts)))
    proposals: List[int] = []
    if drafter is not None and n_drafts > 0:
        proposals = [int(t) for t in drafter(context, n_drafts)][:n_drafts]
        tokens.extend(proposals)
    tokens.extend([-1] * (width - len(tokens)))
    return tokens, n_known, len(proposals)


def verify_window(logits, tokens, n_known, eos_id, rng, active,
                  temperature: float, top_k, top_p):
    """Traced accept/reject over one slot's window (module docstring).

    ``logits`` [W, V] — the target forward's output at every window
    position; ``tokens`` [W] — the window inputs (replay prefix, then
    drafts, then -1 fill); ``n_known``/``eos_id``/``active`` traced
    scalars (eos_id -1 = none); ``rng`` the slot's uint32[2] key.
    Returns ``(emitted [W], n_emitted, rng)``: the tokens this step
    emits, packed from index 0 (entries past ``n_emitted`` are fill),
    and the key advanced by exactly ``n_emitted`` splits.

    Position ``n_known`` always emits (the exact step's one token —
    accept-rate 0 degrades to exactly one token per step); position
    ``i > n_known`` emits iff the chain is alive: every prior draft
    matched the target's own emission and no emitted token was eos.
    The W-step loop is unrolled — W is small and static — so the whole
    pass is branch-free device code: no host syncs, no recompiles from
    tick-varying ``tokens``/``n_known``.
    """
    import jax
    import jax.numpy as jnp

    from tf_yarn_tpu.models.generate import _sample

    width = logits.shape[0]
    emitted = jnp.zeros((width,), jnp.int32)
    count = jnp.asarray(0, jnp.int32)
    emit_prev = jnp.asarray(False)
    out_prev = jnp.asarray(-1, jnp.int32)
    for i in range(width):
        chain_alive = (
            emit_prev & (tokens[i] == out_prev) & (out_prev != eos_id)
        )
        emit_i = active & ((n_known == i) | chain_alive)
        next_rng, sample_key = jax.random.split(rng)
        out_i = _sample(
            logits[i][None], sample_key, temperature, top_k, top_p
        )[0]
        rng = jnp.where(emit_i, next_rng, rng)
        slot_idx = jnp.clip(i - n_known, 0, width - 1)
        written = jax.lax.dynamic_update_slice(
            emitted, out_i[None], (slot_idx,)
        )
        emitted = jnp.where(emit_i, written, emitted)
        count = count + emit_i.astype(jnp.int32)
        emit_prev, out_prev = emit_i, out_i
    return emitted, count, rng
