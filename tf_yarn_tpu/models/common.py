"""Shared loss/metric builders for the model zoo.

Loss contract (tf_yarn_tpu.experiment): ``loss_fn(model, params, batch,
rng) -> (loss, aux)`` with batch a dict of arrays, labels under "y".
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def classification_loss(model, params, batch, rng, train=True):
    """Softmax cross-entropy + accuracy for models mapping x -> logits.
    `train=False` disables dropout (zoo models take `deterministic`)."""
    logits = model.apply(
        params, batch["x"], rngs={"dropout": rng}, deterministic=not train
    )
    labels = batch["y"]
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
    accuracy = jnp.mean(jnp.argmax(logits, axis=-1) == labels)
    return loss, {"accuracy": accuracy}


def binary_logistic_loss(model, params, batch, rng, train=True):
    """Sigmoid cross-entropy for models mapping x -> a single logit."""
    logits = model.apply(
        params, batch["x"], rngs={"dropout": rng}, deterministic=not train
    ).squeeze(-1)
    labels = batch["y"].astype(jnp.float32)
    loss = optax.sigmoid_binary_cross_entropy(logits, labels).mean()
    accuracy = jnp.mean((logits > 0) == (labels > 0.5))
    return loss, {"accuracy": accuracy}


def lm_loss(model, params, batch, rng, train=True):
    """Next-token cross-entropy for causal LMs: batch has "tokens"
    [B, S] int32; loss over positions 0..S-2 predicting 1..S-1.
    MoE models additionally contribute their sown load-balancing loss."""
    tokens = batch["tokens"]
    logits, mod_vars = model.apply(
        params,
        tokens,
        rngs={"dropout": rng},
        deterministic=not train,
        mutable=["intermediates"],
    )
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    if "mask" in batch:
        mask = batch["mask"][:, 1:].astype(loss.dtype)
        loss = (loss * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    else:
        loss = loss.mean()
    aux = {"perplexity": jnp.exp(loss)}
    moe_weight = getattr(getattr(model, "config", None), "moe_aux_weight", 0.0)
    moe_losses = [
        jnp.sum(leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            mod_vars.get("intermediates", {})
        )[0]
        if any("moe_aux_loss" in str(getattr(k, "key", "")) for k in path)
    ]
    if moe_losses and moe_weight:
        moe_total = sum(moe_losses)
        loss = loss + moe_weight * moe_total
        aux["moe_aux_loss"] = moe_total
    return loss, aux


def synthetic_classification_iter(
    batch_size: int, feature_dim: int, num_classes: int, seed: int = 0
):
    """Endless synthetic (x, y) batches — fixed shapes, deterministic."""
    import numpy as np

    rng = np.random.RandomState(seed)
    weights = rng.randn(feature_dim, num_classes).astype(np.float32)
    while True:
        x = rng.randn(batch_size, feature_dim).astype(np.float32)
        y = np.argmax(x @ weights + 0.1 * rng.randn(batch_size, num_classes), axis=-1)
        yield {"x": x, "y": y.astype(np.int32)}


def synthetic_token_iter(batch_size: int, seq_len: int, vocab: int, seed: int = 0):
    import numpy as np

    rng = np.random.RandomState(seed)
    while True:
        yield {"tokens": rng.randint(0, vocab, (batch_size, seq_len), dtype=np.int32)}
