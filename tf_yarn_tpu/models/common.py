"""Shared loss/metric builders for the model zoo.

Loss contract (tf_yarn_tpu.experiment): ``loss_fn(model, params, batch,
rng) -> (loss, aux)`` with batch a dict of arrays, labels under "y".
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def adamw_with_decay_mask(
    learning_rate: float, weight_decay: float = 1e-4
):
    """AdamW that skips weight decay on 1D params (norm scales, biases) —
    the standard transformer recipe. Identical to optax.adamw (same
    default weight_decay) except for the mask."""

    def mask(params):
        return jax.tree_util.tree_map(lambda p: p.ndim > 1, params)

    return optax.adamw(learning_rate, weight_decay=weight_decay, mask=mask)


def classification_loss(model, params, batch, rng, train=True):
    """Softmax cross-entropy + accuracy for models mapping x -> logits.
    `train=False` disables dropout (zoo models take `deterministic`)."""
    logits = model.apply(
        params, batch["x"], rngs={"dropout": rng}, deterministic=not train
    )
    labels = batch["y"]
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
    accuracy = jnp.mean(jnp.argmax(logits, axis=-1) == labels)
    return loss, {"accuracy": accuracy}


def binary_logistic_loss(model, params, batch, rng, train=True):
    """Sigmoid cross-entropy for models mapping x -> a single logit."""
    logits = model.apply(
        params, batch["x"], rngs={"dropout": rng}, deterministic=not train
    ).squeeze(-1)
    labels = batch["y"].astype(jnp.float32)
    loss = optax.sigmoid_binary_cross_entropy(logits, labels).mean()
    accuracy = jnp.mean((logits > 0) == (labels > 0.5))
    return loss, {"accuracy": accuracy}


def lm_loss(model, params, batch, rng, train=True):
    """Next-token cross-entropy for causal LMs: batch has "tokens"
    [B, S] int32; loss over positions 0..S-2 predicting 1..S-1.
    MoE models additionally contribute their sown load-balancing loss."""
    tokens = batch["tokens"]
    logits, mod_vars = model.apply(
        params,
        tokens,
        rngs={"dropout": rng},
        deterministic=not train,
        mutable=["intermediates"],
    )
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    if "mask" in batch:
        mask = batch["mask"][:, 1:].astype(loss.dtype)
        loss = (loss * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    else:
        loss = loss.mean()
    aux = {"perplexity": jnp.exp(loss)}
    loss, aux = _apply_moe_aux(model, mod_vars, loss, aux)
    return loss, aux


def _apply_moe_aux(model, mod_vars, loss, aux):
    """Fold sown MoE load-balancing losses into the task loss (shared by
    the full and chunked LM losses)."""
    moe_weight = getattr(getattr(model, "config", None), "moe_aux_weight", 0.0)
    moe_losses = [
        jnp.sum(leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            mod_vars.get("intermediates", {})
        )[0]
        if any("moe_aux_loss" in str(getattr(k, "key", "")) for k in path)
    ]
    if moe_losses and moe_weight:
        moe_total = sum(moe_losses)
        loss = loss + moe_weight * moe_total
        aux["moe_aux_loss"] = moe_total
    return loss, aux


def lm_loss_chunked(model, params, batch, rng, train=True, chunk_size=8192):
    """Next-token cross-entropy without materializing [B, S, vocab] logits.

    The HBM saver for large-vocab decoders (llama-3's 128k vocab makes
    full f32 logits the single biggest activation): hidden states come out
    of the model once; the head matmul + logsumexp run per vocab chunk
    inside a `lax.scan`, accumulating max/sum-exp online and gathering the
    target logit — O(B*S*chunk) live memory instead of O(B*S*V).
    Same semantics as `lm_loss`, including MoE aux-loss collection.
    """
    tokens = batch["tokens"]
    hidden, mod_vars = model.apply(
        params, tokens, rngs={"dropout": rng}, deterministic=not train,
        return_hidden=True, mutable=["intermediates"],
    )  # [B, S, D]
    head = params["params"]["lm_head"]  # [D, V]
    vocab = head.shape[-1]
    n_chunks = -(-vocab // chunk_size)
    pad = n_chunks * chunk_size - vocab
    if pad:
        head = jnp.pad(head, ((0, 0), (0, pad)))
    head_chunks = head.reshape(head.shape[0], n_chunks, chunk_size)
    head_chunks = jnp.moveaxis(head_chunks, 1, 0)  # [n_chunks, D, chunk]

    h = hidden[:, :-1]  # predict positions 1..S-1
    targets = tokens[:, 1:]
    b, s, d = h.shape
    h2 = h.reshape(b * s, d)
    t2 = targets.reshape(b * s)

    def body(carry, inp):
        m, l, tgt_logit = carry
        chunk_idx, w = inp
        logits = (h2 @ w.astype(h2.dtype)).astype(jnp.float32)  # [BS, chunk]
        base = chunk_idx * chunk_size
        if pad:  # padded tail columns must not contribute
            col = jnp.arange(chunk_size)[None, :] + base
            logits = jnp.where(col < vocab, logits, -1e30)
        m_blk = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1
        )
        # Gather this chunk's target logits where they fall in range.
        local = t2 - base
        in_range = (local >= 0) & (local < chunk_size)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk_size - 1)[:, None], axis=-1
        )[:, 0]
        tgt_logit = jnp.where(in_range, picked, tgt_logit)
        return (m_new, l, tgt_logit), None

    m0 = jnp.full((b * s,), -1e30, jnp.float32)
    l0 = jnp.zeros((b * s,), jnp.float32)
    t0 = jnp.zeros((b * s,), jnp.float32)
    # Remat the chunk body: without it autodiff stacks each chunk's
    # logits-sized residuals across the scan — O(B*S*V) again, exactly
    # what this loss exists to avoid. Recomputing the chunk matmul in the
    # backward keeps the O(B*S*chunk) footprint.
    (m, l, tgt_logit), _ = jax.lax.scan(
        jax.checkpoint(body), (m0, l0, t0), (jnp.arange(n_chunks), head_chunks)
    )
    logsumexp = m + jnp.log(jnp.maximum(l, 1e-30))
    loss_per_tok = (logsumexp - tgt_logit).reshape(b, s)
    if "mask" in batch:
        mask = batch["mask"][:, 1:].astype(jnp.float32)
        loss = (loss_per_tok * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    else:
        loss = loss_per_tok.mean()
    aux = {"perplexity": jnp.exp(loss)}
    loss, aux = _apply_moe_aux(model, mod_vars, loss, aux)
    return loss, aux


def synthetic_classification_iter(
    batch_size: int, feature_dim: int, num_classes: int, seed: int = 0
):
    """Endless synthetic (x, y) batches — fixed shapes, deterministic."""
    import numpy as np

    rng = np.random.RandomState(seed)
    weights = rng.randn(feature_dim, num_classes).astype(np.float32)
    while True:
        x = rng.randn(batch_size, feature_dim).astype(np.float32)
        y = np.argmax(x @ weights + 0.1 * rng.randn(batch_size, num_classes), axis=-1)
        yield {"x": x, "y": y.astype(np.int32)}


def synthetic_token_iter(batch_size: int, seq_len: int, vocab: int, seed: int = 0):
    import numpy as np

    rng = np.random.RandomState(seed)
    while True:
        yield {"tokens": rng.randint(0, vocab, (batch_size, seq_len), dtype=np.int32)}
