"""Persistent compiled decode engine: cached jit + on-device EOS loop.

`models.generate.generate` paid three per-call taxes: a *fresh* jitted
step closure per call (its compile cache died with the call), one host
round-trip per generated token (`bool(finished.all())`), and a compiled
shape per (batch, prompt-len) a caller happened to send. `DecodeEngine`
removes all three:

* **Cached AOT compiles.** Prefill is lowered+compiled once per
  (batch-bucket, prompt-bucket) and the decode loop once per
  batch-bucket; executables live on the engine and are reused across
  batches. Every compile is logged with its key so recompile storms are
  visible, and `stats` counts compiles vs cache hits.

* **On-device decode loop.** The whole token loop is ONE
  `jax.lax.while_loop` inside ONE compiled program: sampling, KV-cache
  append, EOS-finished masking, and the all-finished early-exit
  condition are all traced. Zero device→host transfers per token — the
  only sync is the caller reading the finished sequences. The KV cache
  and the output token buffer are donated (`donate_argnums`), so each
  step updates HBM in place instead of double-buffering the cache.

* **Shape bucketing.** Batch is padded UP to the next configured bucket
  (pad rows are sliced back out). Prompt length is floor-bucketed:
  prefill runs at the largest bucket <= P and the remaining P-F prompt
  tokens are teacher-forced through the device loop (their K/V appended,
  their sampled tokens discarded). Unlike right-padding the prompt, the
  replay is *exact* — cache contents, RoPE positions, and the RNG stream
  match the unbucketed path, so outputs are identical to
  `generate_legacy` — while recompiles stay bounded by the bucket grid.

The loop-trip-count inputs (actual replay length, max_new_tokens, the
eos id, the PRNG seed) are traced scalars, so they never force a
recompile; only shapes and the sampling configuration (temperature /
top_k / top_p are baked into the traced program) key the cache.

* **Tensor-parallel decode.** Constructed with a ``mesh``
  (docs/Serving.md "Tensor-parallel decode"), the engine serves a model
  bigger than one chip's HBM: params place by the transformer's
  logical-axis rules (attention heads / MLP hidden / vocab over the
  ``tp`` mesh axis), every slot KV cache and the paged block pool shard
  their kv-heads axis over ``tp`` (`kv_partition_spec` /
  `pool_partition_spec` — each device holds 1/tp of every slot and
  every block), and all the compiled programs lower with explicit
  in/out shardings so the XLA partitioner inserts the attention-output
  and MLP down-projection all-reduces from the placements alone. No
  scheduler logic changes: still ONE program and one host sync per
  tick, tables/lengths/tokens still traced, and emitted token streams
  identical to the single-device path (float logits agree to roundoff —
  the partitioned matmuls reduce in a different grouping; the emitted
  ints are the tested contract, as with speculative decoding below).

* **Paged KV slots.** The serving grid's dense per-slot caches (each a
  full `max_seq_len` allocation, mostly padding for short requests) have
  a paged alternative: ONE global pool of fixed-size KV blocks
  (`make_paged_pool`) plus a per-slot block table. The compiled
  `paged_step` gathers each slot's dense cache view from the pool by its
  block table, runs the exact same per-slot model step, and
  scatter-appends the new K/V row into the slot's current block — all
  inside one program, zero host syncs per tick. Because the gathered
  view holds the identical values the dense slot cache would (positions
  beyond a slot's length are masked to exactly-zero weight by the
  attention mask), the fp paged path is BIT-IDENTICAL to the dense path
  and to `generate_legacy`. Free/allocate is host-side free-list
  bookkeeping (`serving/paging.py`); there is no per-eviction device
  program at all. `pack_prefill` splices a bucketed-prefill result into
  a slot's blocks; int8 KV composes transparently (the pool stores
  whatever leaves the model's cache has — int8 values + scales
  included).
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from tf_yarn_tpu import telemetry
from tf_yarn_tpu.models.generate import _sample
from tf_yarn_tpu.models.spec import verify_window

_logger = logging.getLogger(__name__)

# Bucket grids: batch is ceil-padded, prompt is floor-bucketed (see
# module docstring). Sizes outside the grid fall back to exact-shape
# compiles, logged as unbucketed.
DEFAULT_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64)
DEFAULT_PROMPT_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)
# The output token buffer is sized in multiples of this, so max_new_tokens
# only recompiles when it crosses a multiple, not on every value.
DEFAULT_TOKEN_BUCKET = 64


def build_prefill_fn(model):
    """(params, prompt [B, F]) -> (cache, last-position logits [B, V])."""

    def prefill(params, prompt):
        logits, state = model.apply(
            params, prompt, decode=True, mutable=["cache"]
        )
        return state["cache"], logits[:, -1]

    return prefill


def build_decode_fn(model, temperature: float, top_k: Optional[int],
                    top_p: Optional[float], has_eos: bool, has_rest: bool):
    """The single-program decode loop, shared by the engine and the
    analysis jaxpr entry points.

    has_rest=True signature:
        fn(params, cache, rest, rest_len, num_new, rng, eos_id, out)
    has_rest=False signature (prompt hit a bucket exactly — the first
    token is sampled from the prefill logits, outside the loop):
        fn(params, cache, last_logits, num_new, rng, eos_id, out)

    `rest_len`, `num_new`, `eos_id` are traced scalars; `out` is the
    preallocated token buffer [B, T] (pre-filled with eos when has_eos,
    so the early-exit tail is already correct). Returns (filled buffer,
    final cache): the caller donates `cache` and `out`, and returning
    the cache gives XLA the output to alias the donated input against —
    the loop carry then updates the prefill cache's HBM in place instead
    of copying it into the program.

    Loop-step semantics mirror generate_legacy exactly, including the
    RNG split chain: replay steps (t < rest_len-1) consume no RNG; the
    step at t == rest_len-1 samples the first generated token with the
    first split (generate_legacy's prefill sample); each later step
    advances the chain once.
    """

    def step_apply(params, cache, token):
        logits, state = model.apply(
            {**params, "cache": cache}, token[:, None], decode=True,
            mutable=["cache"],
        )
        return state["cache"], logits[:, -1]

    def make_loop(params, cache, rest, r, rng, eos_id, out,
                  first_emitted, total):
        w = rest.shape[1] if has_rest else 1
        t_max = out.shape[1]

        def cond(carry):
            _cache, cur, _rng, finished, t, _out = carry
            alive = t < total
            if has_eos:
                # cur is only an emitted token once generation started;
                # during replay the exit check must stay off.
                done = jnp.all(finished | (cur == eos_id))
                alive = alive & ((t < r) | ~done)
            return alive

        def body(carry):
            cache, cur, rng, finished, t, out = carry
            if has_rest:
                col = jax.lax.dynamic_slice_in_dim(
                    rest, jnp.clip(t, 0, w - 1), 1, axis=1
                )[:, 0]
                token_in = jnp.where(t < r, col, cur)
            else:
                token_in = cur
            cache, logits = step_apply(params, cache, token_in)
            # Replay steps before the last consume no RNG and emit
            # nothing — the split chain stays aligned with the
            # unbucketed path's one-split-per-sample.
            do_sample = t >= r - 1
            next_rng, sample_key = jax.random.split(rng)
            rng = jnp.where(do_sample, next_rng, rng)
            sampled = _sample(logits, sample_key, temperature, top_k, top_p)
            if has_eos:
                # Generation steps after the first: a row that already
                # emitted eos keeps emitting eos.
                finished = jnp.where(
                    t >= r, finished | (cur == eos_id), finished
                )
                emit = jnp.where(finished, eos_id, sampled)
            else:
                emit = sampled
            cur = jnp.where(do_sample, emit, cur)
            k = jnp.clip(t - r + 1, 0, t_max - 1)
            written = jax.lax.dynamic_update_slice(
                out, emit[:, None].astype(out.dtype), (0, k)
            )
            out = jnp.where(do_sample, written, out)
            return cache, cur, rng, finished, t + 1, out

        b = out.shape[0]
        finished0 = jnp.zeros((b,), bool)
        carry = (cache, first_emitted, rng, finished0,
                 jnp.asarray(0, jnp.int32), out)
        cache, _cur, _rng, _fin, _t, out = jax.lax.while_loop(
            cond, body, carry
        )
        return out, cache

    if has_rest:
        def decode(params, cache, rest, rest_len, num_new, rng, eos_id, out):
            b = out.shape[0]
            cur0 = jnp.zeros((b,), jnp.int32)
            total = rest_len + num_new - 1
            return make_loop(params, cache, rest, rest_len, rng,
                             eos_id, out, cur0, total)
    else:
        def decode(params, cache, last_logits, num_new, rng, eos_id, out):
            rng, first_key = jax.random.split(rng)
            first = _sample(last_logits, first_key, temperature, top_k, top_p)
            out = jax.lax.dynamic_update_slice(
                out, first[:, None].astype(out.dtype), (0, 0)
            )
            zero = jnp.asarray(0, jnp.int32)
            return make_loop(params, cache, None, zero, rng,
                             eos_id, out, first, num_new - 1)

    return decode


def build_step_fn(model, temperature: float, top_k: Optional[int],
                  top_p: Optional[float]):
    """The continuous-batching slot step, shared by the engine and the
    analysis jaxpr entry point (`models.decode_engine.step`).

        fn(params, slot_cache, tokens, rngs, sample_mask)
            -> (slot_cache, emitted [S], rngs)

    ONE compiled program advances EVERY slot of a serving grid by one
    token. `slot_cache` is the per-slot KV grid (leading slot axis; each
    element a batch-1 decode cache with its own `cache_index`, so slots
    sit at independent positions — the per-slot offsets the shared batch
    cache of `decode_loop` cannot express). `tokens` [S] are this tick's
    inputs: a forced prompt token while a slot replays its prompt
    remainder, else the slot's last emitted token. `sample_mask` [S] is
    the traced active mask: masked-off slots (free, or mid-replay) run
    the same device program — the KV append is the point for replay
    slots, garbage for free ones — but consume no RNG and pass their
    input token through, so each slot's split chain stays bit-aligned
    with generate_legacy's one-split-per-sample. The step that consumes
    a request's LAST prompt token has sample_mask on: its output is the
    first generated token, sampled with the first split — exactly
    generate_legacy's prefill sample.
    """

    def step(params, slot_cache, tokens, rngs, sample_mask):
        def one_slot(cache, token, rng, do_sample):
            logits, state = model.apply(
                {**params, "cache": cache}, token[None, None], decode=True,
                mutable=["cache"],
            )
            next_rng, sample_key = jax.random.split(rng)
            sampled = _sample(
                logits[:, -1], sample_key, temperature, top_k, top_p
            )[0]
            emitted = jnp.where(do_sample, sampled, token)
            rng = jnp.where(do_sample, next_rng, rng)
            return state["cache"], emitted, rng

        return jax.vmap(one_slot)(slot_cache, tokens, rngs, sample_mask)

    return step


# --------------------------------------------------------------------------
# Speculative decoding: the windowed verify steps
# --------------------------------------------------------------------------
#
# One spec tick advances a slot by a VARIABLE number of tokens: the
# target model scores all `width` window positions (replay prefix +
# last token + drafts) in one batched forward, `verify_window`
# (models/spec.py) keeps exactly the prefix the sequential path would
# have emitted, and only the accepted positions become valid KV. The
# forward writes all `width` K/V rows — rejected-draft rows land beyond
# the slot's valid length, where every decode-attention path masks them
# to zero weight and the next tick's window overwrites them — so
# acceptance never needs a device-side KV rollback. Emitted token
# streams are identical to generate_legacy (token-matching acceptance);
# note the windowed forward compiles to a different fusion than the
# one-token step, so float *logits* agree to roundoff, not bitwise —
# the emitted ints are the contract, and the tests pin them.


def _index_leaf_value(cache, max_seq_len: int):
    """The slot's pre-apply position, read from any index leaf (a cache
    leaf with no seq axis; all index leaves carry the same scalar)."""
    for leaf in jax.tree_util.tree_leaves(cache):
        if _seq_axis(leaf.shape, max_seq_len) is None:
            return leaf.reshape(-1)[0].astype(jnp.int32)
    raise ValueError("cache has no index leaf — unknown cache layout")


def _with_index(cache, new_index, max_seq_len: int):
    """Rewrite every index leaf to `new_index` (the accepted length),
    leaving KV leaves untouched."""

    def leaf(value):
        if _seq_axis(value.shape, max_seq_len) is None:
            return jnp.full(value.shape, new_index, value.dtype)
        return value

    return jax.tree_util.tree_map(leaf, cache)


def build_spec_step_fn(model, width: int, temperature: float,
                       top_k: Optional[int], top_p: Optional[float]):
    """The dense speculative slot step, shared by the engine and the
    analysis jaxpr entry point (`models.decode_engine.spec_step`).

        fn(params, slot_cache, tokens [S, W], n_known [S], eos_ids [S],
           rngs [S, 2], active [S])
            -> (slot_cache, emitted [S, W], counts [S], rngs)

    ONE compiled program advances every slot up to W tokens: per slot,
    the target model scores the whole window in one forward (K/V for
    all W positions appended at the slot's cache_index), verify_window
    computes the emitted prefix, and the slot's cache_index is rewritten
    to `old_index + n_known + n_emitted` — the accepted length — so
    rejected rows are dead weight the next window overwrites. Inactive
    slots (active=False) emit nothing, consume no RNG, and keep their
    cache_index; their garbage window rows land in their own (free)
    cache and are overwritten at the next admission. tokens / n_known /
    eos_ids are traced, so tick-to-tick changes never recompile.

    This program is ALSO the chunk-apply for chunked prefill
    (docs/Serving.md "Chunked prefill"): a window whose tokens are all
    pending prompt tokens (n_known == W) is a teacher-forced chunk —
    the forward appends W prompt positions of KV and emits nothing.
    The scheduler widens W to max(spec_k + 1, prefill_chunk); it is a
    compile-key dimension, fixed per grid, so chunking adds zero
    recompiles.
    """
    max_seq_len = model.config.max_seq_len

    def spec_step(params, slot_cache, tokens, n_known, eos_ids, rngs,
                  active):
        def one_slot(cache, toks, known, eos_id, rng, act):
            idx = _index_leaf_value(cache, max_seq_len)
            logits, state = model.apply(
                {**params, "cache": cache}, toks[None, :], decode=True,
                mutable=["cache"],
            )
            emitted, count, rng = verify_window(
                logits[0], toks, known, eos_id, rng, act,
                temperature, top_k, top_p,
            )
            n_valid = jnp.where(act, known + count, 0)
            cache = _with_index(state["cache"], idx + n_valid, max_seq_len)
            return cache, emitted, count, rng

        return jax.vmap(one_slot)(
            slot_cache, tokens, n_known, eos_ids, rngs, active
        )

    return spec_step


# --------------------------------------------------------------------------
# Paged KV layout: pool avals + the compiled gather/scatter programs
# --------------------------------------------------------------------------

def _seq_axis(shape: Tuple[int, ...], max_seq_len: int) -> Optional[int]:
    """Index of the cache leaf's sequence axis (the one sized
    max_seq_len), or None for non-KV leaves (cache_index). Raises on an
    ambiguous layout — a config where some other cache dimension equals
    max_seq_len needs a different block_size/max_seq_len split, not a
    silent guess."""
    matches = [i for i, dim in enumerate(shape) if dim == max_seq_len]
    if len(matches) > 1:
        raise ValueError(
            f"ambiguous KV cache leaf {shape}: {len(matches)} axes equal "
            f"max_seq_len={max_seq_len}; the paged layout needs exactly one"
        )
    return matches[0] if matches else None


def _decode_cache_aval(model, params):
    """Abstract batch-1 decode cache (the slot row shape). Works with
    traced or concrete params — eval_shape never touches the device."""
    return jax.eval_shape(
        build_prefill_fn(model), params,
        jax.ShapeDtypeStruct((1, 1), jnp.int32),
    )[0]


def paged_pool_avals(row_aval, num_blocks: int, block_size: int,
                     max_seq_len: int):
    """The pool pytree's avals: every KV leaf's seq axis becomes
    (num_blocks, block_size); index leaves (no seq axis) become None —
    per-slot positions travel as the step's `lengths` argument instead
    of living in the cache."""
    if max_seq_len % block_size:
        raise ValueError(
            f"block_size={block_size} must divide max_seq_len={max_seq_len}"
        )

    def leaf(aval):
        ax = _seq_axis(aval.shape, max_seq_len)
        if ax is None:
            if not jnp.issubdtype(aval.dtype, jnp.integer):
                raise ValueError(
                    f"cache leaf {aval.shape}/{aval.dtype} has no "
                    f"max_seq_len={max_seq_len} axis and is not an index "
                    "leaf — unknown cache layout for paging"
                )
            return None
        shape = aval.shape[:ax] + (num_blocks, block_size) + aval.shape[ax + 1:]
        return jax.ShapeDtypeStruct(shape, aval.dtype)

    return jax.tree_util.tree_map(leaf, row_aval)


def _is_none(x) -> bool:
    return x is None


def _is_named_sharding(sharding) -> bool:
    from jax.sharding import NamedSharding

    return isinstance(sharding, NamedSharding)


def _gather_slot_cache(pool, row_aval, table, length, max_seq_len):
    """One slot's dense cache view: KV leaves gathered from the pool by
    the block table (and reshaped back to the dense seq axis), index
    leaves filled with the slot's length. Values beyond `length` are
    stale pool garbage — every decode-attention path masks positions >=
    cache_index to exactly-zero weight, so the view is value-identical
    to a dense slot cache where it matters (bit-identity relies on
    this)."""

    def leaf(pool_leaf, aval):
        if pool_leaf is None:
            return jnp.full(aval.shape, length, aval.dtype)
        ax = _seq_axis(aval.shape, max_seq_len)
        return jnp.take(pool_leaf, table, axis=ax).reshape(aval.shape)

    return jax.tree_util.tree_map(leaf, pool, row_aval, is_leaf=_is_none)


def build_paged_step_fn(model, block_size: int, temperature: float,
                        top_k: Optional[int], top_p: Optional[float]):
    """The paged continuous-batching step, shared by the engine and the
    analysis jaxpr entry point (`models.decode_engine.paged_step`).

        fn(params, pool, tables, lengths, tokens, rngs, sample_mask)
            -> (pool, emitted [S], rngs)

    ONE compiled program advances every slot one token against the
    global block pool: per slot, gather its dense cache view through its
    block-table row, run the identical per-slot model step
    `build_step_fn` runs (same sampling, same RNG discipline — masked
    slots consume no RNG and pass their token through), then
    scatter-append the freshly written K/V row into block
    `table[length // block_size]` at offset `length % block_size`.
    `tables`/`lengths` are traced values — tick-to-tick table changes
    never recompile. Inactive slots carry an all-zero table row and
    length 0, so their (meaningless) write lands in the reserved trash
    block 0 and can never corrupt a live slot.
    """
    max_seq_len = model.config.max_seq_len

    def step(params, pool, tables, lengths, tokens, rngs, sample_mask):
        row_aval = _decode_cache_aval(model, params)

        def one_slot(table, length, token, rng, do_sample):
            cache = _gather_slot_cache(
                pool, row_aval, table, length, max_seq_len
            )
            logits, state = model.apply(
                {**params, "cache": cache}, token[None, None], decode=True,
                mutable=["cache"],
            )
            next_rng, sample_key = jax.random.split(rng)
            sampled = _sample(
                logits[:, -1], sample_key, temperature, top_k, top_p
            )[0]
            emitted = jnp.where(do_sample, sampled, token)
            rng = jnp.where(do_sample, next_rng, rng)

            def new_row(leaf, aval):
                ax = _seq_axis(aval.shape, max_seq_len)
                if ax is None:
                    return None
                return jax.lax.dynamic_slice_in_dim(leaf, length, 1, axis=ax)

            rows = jax.tree_util.tree_map(new_row, state["cache"], row_aval)
            return emitted, rng, rows

        emitted, rngs, rows = jax.vmap(
            one_slot, in_axes=(0, 0, 0, 0, 0)
        )(tables, lengths, tokens, rngs, sample_mask)

        slots = tables.shape[0]

        def write(pool_leaf, slot_rows, aval):
            if pool_leaf is None:
                return None
            ax = _seq_axis(aval.shape, max_seq_len)
            for s in range(slots):
                block = tables[s, lengths[s] // block_size]
                offset = lengths[s] % block_size
                update = jnp.expand_dims(slot_rows[s], ax)
                starts = [jnp.asarray(0, jnp.int32)] * pool_leaf.ndim
                starts[ax] = block
                starts[ax + 1] = offset
                pool_leaf = jax.lax.dynamic_update_slice(
                    pool_leaf, update.astype(pool_leaf.dtype), tuple(starts)
                )
            return pool_leaf

        pool_out = jax.tree_util.tree_map(
            write, pool, rows, row_aval, is_leaf=_is_none
        )
        return pool_out, emitted, rngs

    return step


DECODE_ATTENTION_MODES = ("gather", "fused")


def _prune_none_tree(tree):
    """The pool tree minus its None (elided index) entries — the shape
    flax accepts as the `kv_pool` variable collection (its nested dict
    structure mirrors the cache collection by construction)."""
    if isinstance(tree, dict):
        out = {}
        for key, value in tree.items():
            pruned = _prune_none_tree(value)
            if pruned is None or (isinstance(pruned, dict) and not pruned):
                continue
            out[key] = pruned
        return out
    return tree


def _merge_pool_tree(pool, updated):
    """Fold the model's updated `kv_pool` collection back into the
    engine's pool structure (None index leaves restored in place)."""
    if pool is None:
        return None
    if isinstance(pool, dict):
        return {
            key: _merge_pool_tree(
                value, updated[key] if key in updated else None
            )
            for key, value in pool.items()
        }
    return pool if updated is None else updated


def build_paged_spec_step_fn(model, block_size: int, width: int,
                             temperature: float, top_k: Optional[int],
                             top_p: Optional[float],
                             decode_attention: str = "gather"):
    """The paged speculative slot step, shared by the engine and the
    analysis jaxpr entry point (`models.decode_engine.paged_spec_step`).

        fn(params, pool, tables, lengths, tokens [S, W], n_known [S],
           eos_ids [S], rngs [S, 2], active [S])
            -> (pool, emitted [S, W], counts [S], rngs)

    Same verify semantics as `build_spec_step_fn` over the block pool;
    the slot's valid length is the HOST's `lengths` bookkeeping (it
    advances by n_known + n_emitted after the tick), so the program
    itself needs no index fixup. All `width` freshly written K/V rows
    scatter back at logical positions length..length+W-1 — rows beyond
    a slot's reserved blocks hit table entries 0 and land in the trash
    block, so rejected drafts can never touch another slot's KV. Like
    the dense twin, this doubles as the chunk-apply for chunked prefill:
    an all-known window (n_known == W) writes W prompt rows through the
    block table and emits nothing.

    `decode_attention` picks the attention implementation inside the
    verify forward:

    * ``"gather"`` — materialize each slot's dense cache view from the
      pool (exactly `paged_step`'s path) and run the model's standard
      decode attention over it. Reference semantics.
    * ``"fused"`` — int8 pools only: the model's decode attention reads
      the block pool DIRECTLY through `paged_int8_window_attention`
      (ops/decode_attention.py — block tables ride in SMEM via scalar
      prefetch), the window's K/V rows quantize and scatter into the
      pool before the kernel runs, and no dense per-slot view is ever
      materialized. Numerics differ from the gather path only by
      reduction order (tolerance-tested).
    """
    if decode_attention not in DECODE_ATTENTION_MODES:
        raise ValueError(
            f"decode_attention must be one of {DECODE_ATTENTION_MODES}, "
            f"got {decode_attention!r}"
        )
    max_seq_len = model.config.max_seq_len

    if decode_attention == "fused":
        if getattr(model.config, "kv_cache_dtype", None) != "int8":
            raise ValueError(
                "decode_attention='fused' reads the int8 block pool "
                "directly (paged_int8_window_attention); it requires "
                "kv_cache_dtype='int8'"
            )

        def spec_step_fused(params, pool, tables, lengths, tokens,
                            n_known, eos_ids, rngs, active):
            logits, state = model.apply(
                {**params, "kv_pool": _prune_none_tree(pool)},
                tokens, decode=True, paged_ctx=(tables, lengths),
                mutable=["kv_pool"],
            )
            pool_out = _merge_pool_tree(pool, dict(state["kv_pool"]))

            def vw(row_logits, toks, known, eos_id, rng, act):
                return verify_window(
                    row_logits, toks, known, eos_id, rng, act,
                    temperature, top_k, top_p,
                )

            emitted, counts, rngs = jax.vmap(vw)(
                logits, tokens, n_known, eos_ids, rngs, active
            )
            return pool_out, emitted, counts, rngs

        return spec_step_fused

    def spec_step(params, pool, tables, lengths, tokens, n_known,
                  eos_ids, rngs, active):
        row_aval = _decode_cache_aval(model, params)
        blocks_per_slot = tables.shape[1]

        def one_slot(table, length, toks, known, eos_id, rng, act):
            cache = _gather_slot_cache(
                pool, row_aval, table, length, max_seq_len
            )
            logits, state = model.apply(
                {**params, "cache": cache}, toks[None, :], decode=True,
                mutable=["cache"],
            )
            emitted, count, rng = verify_window(
                logits[0], toks, known, eos_id, rng, act,
                temperature, top_k, top_p,
            )

            def new_rows(leaf, aval):
                ax = _seq_axis(aval.shape, max_seq_len)
                if ax is None:
                    return None
                return jax.lax.dynamic_slice_in_dim(
                    leaf, length, width, axis=ax
                )

            rows = jax.tree_util.tree_map(new_rows, state["cache"], row_aval)
            return emitted, count, rng, rows

        emitted, counts, rngs, rows = jax.vmap(one_slot)(
            tables, lengths, tokens, n_known, eos_ids, rngs, active
        )

        slots = tables.shape[0]

        def write(pool_leaf, slot_rows, aval):
            if pool_leaf is None:
                return None
            ax = _seq_axis(aval.shape, max_seq_len)
            for s in range(slots):
                for w in range(width):
                    pos = lengths[s] + w
                    logical = pos // block_size
                    # Beyond the table (a rejected row past the slot's
                    # reservation): route to the trash block.
                    block = jnp.where(
                        logical < blocks_per_slot,
                        tables[s, jnp.clip(logical, 0, blocks_per_slot - 1)],
                        0,
                    )
                    offset = pos % block_size
                    update = jnp.expand_dims(
                        jax.lax.slice_in_dim(
                            slot_rows[s], w, w + 1, axis=ax
                        ),
                        ax,
                    )
                    starts = [jnp.asarray(0, jnp.int32)] * pool_leaf.ndim
                    starts[ax] = block
                    starts[ax + 1] = offset
                    pool_leaf = jax.lax.dynamic_update_slice(
                        pool_leaf, update.astype(pool_leaf.dtype),
                        tuple(starts),
                    )
            return pool_leaf

        pool_out = jax.tree_util.tree_map(
            write, pool, rows, row_aval, is_leaf=_is_none
        )
        return pool_out, emitted, counts, rngs

    return spec_step


def build_pack_prefill_fn(model, block_size: int, prefill_len: int):
    """The prefill->pool splice program: write positions [0, prefill_len)
    of a freshly prefilled batch-1 cache into the slot's first
    ceil(prefill_len / block_size) blocks.

        fn(pool, block_ids, row_cache) -> pool

    `block_ids` values are traced (different slots reuse one compiled
    program); `prefill_len` is static (one program per prefill bucket).
    """
    max_seq_len = model.config.max_seq_len
    n_pack = -(-prefill_len // block_size)

    def pack(pool, block_ids, row_cache):
        def leaf(pool_leaf, row_leaf):
            if pool_leaf is None:
                return None
            ax = _seq_axis(row_leaf.shape, max_seq_len)
            if ax is None:
                return pool_leaf
            for j in range(n_pack):
                width = min(block_size, prefill_len - j * block_size)
                chunk = jax.lax.slice_in_dim(
                    row_leaf, j * block_size, j * block_size + width, axis=ax
                )
                if width < block_size:
                    pad = [(0, 0)] * chunk.ndim
                    pad[ax] = (0, block_size - width)
                    chunk = jnp.pad(chunk, pad)
                chunk = jnp.expand_dims(chunk, ax)
                starts = [jnp.asarray(0, jnp.int32)] * pool_leaf.ndim
                starts[ax] = block_ids[j]
                pool_leaf = jax.lax.dynamic_update_slice(
                    pool_leaf, chunk.astype(pool_leaf.dtype), tuple(starts)
                )
            return pool_leaf

        return jax.tree_util.tree_map(
            leaf, pool, row_cache, is_leaf=_is_none
        )

    return pack


def build_extract_blocks_fn(model, row_aval):
    """The swap-out gather program: read W pool blocks in one bulk op.

        fn(pool, block_ids) -> payload

    `block_ids` is a traced (W,) int32 vector (W static from its
    shape), so ONE compiled program serves every suspend regardless of
    which physical blocks a slot holds — the scheduler pads short id
    vectors with the trash block and discards those rows host-side.
    The payload pytree mirrors the pool (index leaves stay None) with
    the block axis narrowed to W, in the pool's own dtype — an int8
    pool swaps as quantized bytes. Pure gather: no host callbacks
    (TYA103), so the only host hop is the caller's `device_get`.
    """
    max_seq_len = model.config.max_seq_len

    def extract(pool, block_ids):
        def leaf(pool_leaf, aval):
            if pool_leaf is None:
                return None
            ax = _seq_axis(aval.shape, max_seq_len)
            return jnp.take(pool_leaf, block_ids, axis=ax)

        return jax.tree_util.tree_map(leaf, pool, row_aval,
                                      is_leaf=_is_none)

    return extract


def build_inject_blocks_fn(model, row_aval):
    """The swap-in scatter program, inverse of `build_extract_blocks_fn`:

        fn(pool, block_ids, payload) -> pool

    Writes payload row j into physical block `block_ids[j]` (traced
    values, static width) via the same dynamic_update_slice splice as
    `build_pack_prefill_fn`. The pool is donated by the engine wrapper
    so resume updates HBM in place. Rows the scheduler does not want
    re-injected (prefix-cache hits re-attached by lookup, padding) are
    aimed at the trash block, whose content is garbage by contract.
    """
    max_seq_len = model.config.max_seq_len

    def inject(pool, block_ids, payload):
        def leaf(pool_leaf, aval, pay_leaf):
            if pool_leaf is None:
                return None
            ax = _seq_axis(aval.shape, max_seq_len)
            for j in range(block_ids.shape[0]):
                chunk = jax.lax.slice_in_dim(pay_leaf, j, j + 1, axis=ax)
                starts = [jnp.asarray(0, jnp.int32)] * pool_leaf.ndim
                starts[ax] = block_ids[j]
                pool_leaf = jax.lax.dynamic_update_slice(
                    pool_leaf, chunk.astype(pool_leaf.dtype), tuple(starts)
                )
            return pool_leaf

        return jax.tree_util.tree_map(leaf, pool, row_aval, payload,
                                      is_leaf=_is_none)

    return inject


def cache_nbytes(tree) -> int:
    """Resident bytes of a cache pytree (dense slot grid or paged pool;
    None leaves — elided index leaves — count zero). GLOBAL bytes: a
    tp-sharded tree's per-device share is `tree_nbytes_per_device`."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        size = 1
        for dim in leaf.shape:
            size *= dim
        total += size * jnp.dtype(leaf.dtype).itemsize
    return total


def tree_nbytes_per_device(tree) -> int:
    """Resident bytes of a pytree on EACH device: sharded leaves count
    one shard (`Sharding.shard_shape`), replicated/host leaves count
    whole. With no mesh this equals `cache_nbytes` — the number the
    `serving/kv_cache_hbm_bytes_per_device` gauge and the tp HBM
    accounting tests read."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = tuple(leaf.shape)
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and hasattr(sharding, "shard_shape"):
            shape = sharding.shard_shape(shape)
        size = 1
        for dim in shape:
            size *= dim
        total += size * jnp.dtype(leaf.dtype).itemsize
    return total


# --------------------------------------------------------------------------
# Tensor-parallel decode: the KV placement rule
# --------------------------------------------------------------------------
#
# Under a tp mesh (docs/Serving.md "Tensor-parallel decode") the slot
# KV lives sharded: every cache leaf's kv-heads axis — the axis right
# after the sequence axis in the model's [*, seq, kv_heads, head_dim]
# cache layout (scales ride as [*, seq, kv_heads, 1]) — splits over the
# `tp` mesh axis, so each device holds 1/tp of every slot's cache (and
# of every paged block). Index leaves and layouts whose heads dim does
# not divide stay replicated. Weights place through the transformer's
# EXISTING logical-axis rules (parallel/sharding.py LOGICAL_RULES):
# attention heads + MLP hidden + vocab over tp, the rest replicated on
# a serving mesh — XLA then inserts the attention-output and MLP
# down-projection all-reduces from the shardings alone; no step-program
# logic changes.


def kv_partition_spec(shape: Tuple[int, ...], max_seq_len: int, tp: int):
    """PartitionSpec for a DENSE cache leaf (prefill row, slot row, or
    slot grid — the rule anchors on the seq axis, so the extra leading
    slot/layer axes need no special casing)."""
    from jax.sharding import PartitionSpec

    from tf_yarn_tpu.parallel.mesh import AXIS_TP

    if tp <= 1:
        return PartitionSpec()
    ax = _seq_axis(shape, max_seq_len)
    if ax is None:
        return PartitionSpec()
    heads = ax + 1
    if heads >= len(shape) or shape[heads] % tp:
        return PartitionSpec()
    spec = [None] * len(shape)
    spec[heads] = AXIS_TP
    return PartitionSpec(*spec)


def pool_partition_spec(row_shape: Tuple[int, ...], max_seq_len: int,
                        tp: int):
    """The same heads-axis rule for a PAGED pool leaf, whose seq axis
    was split into (num_blocks, block_size) — computed from the dense
    ROW leaf's shape (the pool shape cannot anchor on max_seq_len), with
    every axis after the split shifted one right."""
    from jax.sharding import PartitionSpec

    from tf_yarn_tpu.parallel.mesh import AXIS_TP

    if tp <= 1:
        return PartitionSpec()
    ax = _seq_axis(row_shape, max_seq_len)
    if ax is None:
        return PartitionSpec()
    heads = ax + 1
    if heads >= len(row_shape) or row_shape[heads] % tp:
        return PartitionSpec()
    spec = [None] * (len(row_shape) + 1)
    spec[heads + 1] = AXIS_TP
    return PartitionSpec(*spec)


def _ceil_bucket(value: int, buckets: Tuple[int, ...]) -> Optional[int]:
    for b in sorted(buckets):
        if b >= value:
            return b
    return None


def _floor_bucket(value: int, buckets: Tuple[int, ...]) -> Optional[int]:
    best = None
    for b in sorted(buckets):
        if b <= value:
            best = b
    return best


class DecodeEngine:
    """Persistent compiled generation for one model (see module docstring).

    Thread-safe for the compile cache; concurrent `generate` calls are
    serialized only while looking up / inserting executables.
    """

    def __init__(
        self,
        model,
        batch_buckets: Tuple[int, ...] = DEFAULT_BATCH_BUCKETS,
        prompt_buckets: Tuple[int, ...] = DEFAULT_PROMPT_BUCKETS,
        token_bucket: int = DEFAULT_TOKEN_BUCKET,
        mesh=None,
    ):
        if token_bucket < 1:
            raise ValueError(f"token_bucket must be >= 1, got {token_bucket}")
        self.model = model
        # Tensor-parallel decode (docs/Serving.md): with a mesh, params
        # place by the model's logical-axis annotations, slot KV shards
        # its kv-heads axis over tp, and every compiled program lowers
        # with explicit in/out shardings so XLA inserts the TP
        # collectives — validated HERE, before any trace, so a bad tp
        # config fails with a config error instead of a partitioner one.
        self.mesh = mesh
        self.tp_degree = 1
        self._rep_sharding = None
        self._param_shardings = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            from tf_yarn_tpu.parallel import sharding as sharding_lib
            from tf_yarn_tpu.parallel.mesh import AXIS_TP, mesh_axis_size

            config = getattr(model, "config", None)
            if config is None or not hasattr(config, "max_seq_len"):
                raise ValueError(
                    "DecodeEngine(mesh=...) needs a model with "
                    "config.max_seq_len — the KV sharding rule anchors "
                    "on the cache's sequence axis"
                )
            self.tp_degree = int(mesh_axis_size(mesh, AXIS_TP))
            for name in ("n_heads", "n_kv_heads"):
                value = getattr(config, name, None)
                if value is not None and value % self.tp_degree:
                    raise ValueError(
                        f"model config {name}={value} does not divide "
                        f"over tp={self.tp_degree} — tensor-parallel "
                        "decode shards attention (and the KV cache) by "
                        "heads; pick a tp that divides both head counts"
                    )
            self._rep_sharding = NamedSharding(mesh, PartitionSpec())
            try:
                abstract = jax.eval_shape(
                    lambda r, t: model.init(r, t),
                    jax.ShapeDtypeStruct((2,), jnp.uint32),
                    jax.ShapeDtypeStruct((1, 8), jnp.int32),
                )
            except Exception as exc:
                raise ValueError(
                    "DecodeEngine(mesh=...) could not abstractly init "
                    f"{type(model).__name__} to read its logical-axis "
                    f"annotations: {type(exc).__name__}: {exc}"
                ) from exc
            self._param_shardings = sharding_lib.tree_shardings(
                mesh, abstract
            )
        self.batch_buckets = tuple(sorted(set(batch_buckets)))
        self.prompt_buckets = tuple(sorted(set(prompt_buckets)))
        self.token_bucket = int(token_bucket)
        # One rest-buffer width for every bucketed prompt interval keeps
        # the decode program shared across prompt buckets: the replay
        # remainder is at most the widest gap in the grid.
        gaps = [b2 - b1 for b1, b2 in zip(self.prompt_buckets,
                                          self.prompt_buckets[1:])]
        self._rest_width = max(gaps) if gaps else 1
        self._prefill: Dict[tuple, Any] = {}
        self._decode: Dict[tuple, Any] = {}
        self._step: Dict[tuple, Any] = {}
        self._lock = threading.Lock()
        self.stats = {
            "calls": 0,
            "prefill_compiles": 0,
            "decode_compiles": 0,
            "step_compiles": 0,
            "prefill_cache_hits": 0,
            "decode_cache_hits": 0,
            "step_cache_hits": 0,
            "paged_step_compiles": 0,
            "paged_step_cache_hits": 0,
            "pack_compiles": 0,
            "pack_cache_hits": 0,
            "spec_step_compiles": 0,
            "spec_step_cache_hits": 0,
            "paged_spec_step_compiles": 0,
            "paged_spec_step_cache_hits": 0,
            "extract_compiles": 0,
            "extract_cache_hits": 0,
            "inject_compiles": 0,
            "inject_cache_hits": 0,
            "unbucketed_shapes": 0,
            "oversize_batch_chunks": 0,
        }
        self._paged_step: Dict[tuple, Any] = {}
        self._pack: Dict[tuple, Any] = {}
        self._spec_step: Dict[tuple, Any] = {}
        self._paged_spec_step: Dict[tuple, Any] = {}
        self._extract: Dict[tuple, Any] = {}
        self._inject: Dict[tuple, Any] = {}

        # Slot-grid splice helpers (continuous batching): donated, so the
        # grid updates HBM in place instead of copying the whole KV store
        # per admission/retirement.
        def _insert(grid, row, slot):
            return jax.tree_util.tree_map(
                lambda buf, r: jax.lax.dynamic_update_index_in_dim(
                    buf, r.astype(buf.dtype), slot, 0
                ),
                grid, row,
            )

        def _evict(grid, slot):
            return jax.tree_util.tree_map(
                lambda buf: jax.lax.dynamic_update_index_in_dim(
                    buf, jnp.zeros(buf.shape[1:], buf.dtype), slot, 0
                ),
                grid,
            )

        self._insert_jit = jax.jit(_insert, donate_argnums=(0,))
        self._evict_jit = jax.jit(_evict, donate_argnums=(0,))

    # -- bucket selection --------------------------------------------------

    def select_buckets(self, batch: int, prompt_len: int) -> Tuple[int, int]:
        """(padded batch, prefill length) for an incoming [B, P] batch.

        Batch pads UP (extra rows are discarded); prompt floors DOWN
        (the remainder replays through the decode loop). Out-of-grid
        sizes return themselves — an exact-shape, logged compile.
        """
        b_bucket = _ceil_bucket(batch, self.batch_buckets) or batch
        p_bucket = _floor_bucket(prompt_len, self.prompt_buckets) or prompt_len
        # A remainder wider than the rest buffer (prompt beyond the
        # grid) cannot replay — prefill the exact length instead.
        if prompt_len - p_bucket > self._rest_width:
            p_bucket = prompt_len
        return b_bucket, p_bucket

    def _params_fingerprint(self, params) -> int:
        leaves, treedef = jax.tree_util.tree_flatten(params)
        return hash((treedef, tuple(
            (tuple(leaf.shape), str(leaf.dtype)) for leaf in leaves
        )))

    # -- tensor-parallel placement -----------------------------------------

    def _place_params(self, params):
        """Every public entry's param normalization: host arrays become
        device arrays, and under a mesh every leaf lands on the
        placement the model's logical-axis annotations assign (a no-op
        transfer-wise once placed — sharded restores arrive here
        already placed by inference.shard_restored_params)."""
        params = jax.tree_util.tree_map(jnp.asarray, params)
        if self.mesh is None:
            return params

        def _place(leaf, sharding):
            if getattr(leaf, "sharding", None) == sharding:
                return leaf
            return jax.device_put(leaf, sharding)

        try:
            return jax.tree_util.tree_map(
                _place, params, self._param_shardings
            )
        except ValueError as exc:
            raise ValueError(
                "params do not match the model's init structure — "
                f"cannot place them on the tp mesh: {exc}"
            ) from exc

    def _shardings_of(self, tree):
        """The committed shardings of a concrete tree (the donated
        grid/pool argument): used as the program's matching OUT
        shardings so the donated buffer aliases instead of copying.
        Host/numpy leaves read as replicated."""
        return jax.tree_util.tree_map(
            lambda leaf: (
                leaf.sharding
                if _is_named_sharding(getattr(leaf, "sharding", None))
                else self._rep_sharding
            ),
            tree,
        )

    def _arg_shardings(self, args) -> tuple:
        """Per-argument in_shardings for a sharded program lowering:
        committed mesh placements pass through (params, the KV
        grid/pool), everything else — the scheduler's per-tick numpy
        tables/lengths/tokens/rngs/masks — is replicated."""
        return tuple(self._shardings_of(arg) for arg in args)

    def _jit(self, fn, args, donate=(), out_shardings=None):
        """jax.jit wired for this engine's mesh: explicit in/out
        shardings under tensor parallelism (XLA inserts the TP
        collectives from these alone), the plain single-device jit
        otherwise."""
        if self.mesh is None:
            return jax.jit(fn, donate_argnums=donate)
        kwargs: Dict[str, Any] = {
            "donate_argnums": donate,
            "in_shardings": self._arg_shardings(args),
        }
        if out_shardings is not None:
            kwargs["out_shardings"] = out_shardings
        return jax.jit(fn, **kwargs)

    def _kv_shardings(self, avals):
        """NamedSharding tree for a DENSE cache tree (row, grid, or
        prefill output) under this engine's mesh: kv-heads axis over
        tp (kv_partition_spec)."""
        from jax.sharding import NamedSharding

        max_seq_len = self.model.config.max_seq_len
        return jax.tree_util.tree_map(
            lambda aval: NamedSharding(
                self.mesh,
                kv_partition_spec(
                    tuple(aval.shape), max_seq_len, self.tp_degree
                ),
            ),
            avals,
        )

    # -- compile cache -----------------------------------------------------

    def _compiled(self, cache_dict, key, stat_prefix, build):
        registry = telemetry.get_registry()
        with self._lock:
            compiled = cache_dict.get(key)
            if compiled is not None:
                self.stats[f"{stat_prefix}_cache_hits"] += 1
                registry.counter(
                    "decode_engine/cache_hits", kind=stat_prefix
                ).inc()
                return compiled
        # Compile outside the lock (slow); a racing duplicate compile is
        # harmless — last writer wins, both executables are equivalent.
        with telemetry.span(
            "decode_engine/compile", kind=stat_prefix, key=str(key)
        ) as sp:
            compiled = build()
        registry.counter("decode_engine/compiles", kind=stat_prefix).inc()
        registry.histogram(
            "decode_engine/compile_seconds", kind=stat_prefix
        ).observe(sp.duration)
        with self._lock:
            cache_dict[key] = compiled
            self.stats[f"{stat_prefix}_compiles"] += 1
            _logger.info(
                "decode-engine compiled %s program for key=%s "
                "(%d %s compiles, %d cached)",
                stat_prefix, key, self.stats[f"{stat_prefix}_compiles"],
                stat_prefix, len(cache_dict),
            )
        return compiled

    def _compiled_prefill(self, params, prompt, fp):
        """(cache, last-position logits) through the compile cache; the
        exact [B, F] shape keys the cache — callers pick bucketed
        shapes."""
        b, f = prompt.shape
        prefill_key = (b, f, fp)
        prefill_fn = build_prefill_fn(self.model)
        prefill_args = (params, prompt)
        def build():
            out_shardings = None
            if self.mesh is not None:
                # Pin the fresh cache SHARDED at the source: everything
                # downstream (insert_slot, pack_prefill) then propagates
                # the placement instead of guessing it. The eval_shape
                # runs only on a compile miss — not per admission.
                cache_avals, _logits_aval = jax.eval_shape(
                    prefill_fn, *prefill_args
                )
                out_shardings = (
                    self._kv_shardings(cache_avals), self._rep_sharding,
                )
            return self._jit(
                prefill_fn, prefill_args, out_shardings=out_shardings
            ).lower(*prefill_args).compile()

        compiled = self._compiled(
            self._prefill, prefill_key, "prefill", build,
        )
        # Dispatch-side span: async device futures, so this times the
        # enqueue (host cost), not the device compute — the XLA profiler
        # owns the device side.
        with telemetry.span("decode_engine/prefill", batch=b, prompt=f):
            return compiled(*prefill_args)

    # -- continuous-batching slot API --------------------------------------
    #
    # The serving scheduler (tf_yarn_tpu/serving/scheduler.py) keeps a
    # fixed grid of `max_slots` decode slots, each backed by a persistent
    # batch-1 KV cache with its own cache_index. Admission prefills a
    # request's prompt through the SAME bucketed prefill programs
    # `generate` uses and splices the result into a free slot; every tick
    # then advances all slots one token in one compiled `step` program.

    def slot_prefill_len(self, prompt_len: int) -> int:
        """Prefill length for a slot admission: the largest prompt bucket
        that still leaves >= 1 prompt token to replay through `step` (the
        step consuming the LAST prompt token samples the first generated
        token — generate_legacy's prefill sample — so the final prompt
        position always goes through the step program). 0 = no prefill:
        the whole prompt replays token-by-token from an empty slot."""
        if prompt_len <= 1:
            return 0
        return _floor_bucket(prompt_len - 1, self.prompt_buckets) or 0

    def prefill(self, params, prompt):
        """Public compiled prefill: [B, F] prompt -> (cache, last
        logits). B/F key the compile cache directly."""
        params = self._place_params(params)
        prompt = jnp.asarray(prompt, jnp.int32)
        return self._compiled_prefill(
            params, prompt, self._params_fingerprint(params)
        )

    def make_slot_cache(self, params, max_slots: int):
        """Zeroed per-slot KV grid: every leaf of the model's decode
        cache stacked along a new leading slot axis (batch-1 per slot,
        per-slot cache_index). Shapes come from an abstract prefill —
        nothing runs on the device except the zeros allocation."""
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        params = self._place_params(params)
        cache_avals = jax.eval_shape(
            build_prefill_fn(self.model), params,
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        )[0]

        def build():
            return jax.tree_util.tree_map(
                lambda leaf: jnp.zeros(
                    (max_slots,) + leaf.shape, leaf.dtype
                ),
                cache_avals,
            )

        if self.mesh is None:
            return build()
        # Sharded zeros straight onto the mesh — each device allocates
        # only its 1/tp shard, no full-grid staging anywhere.
        grid_avals = jax.tree_util.tree_map(
            lambda leaf: jax.ShapeDtypeStruct(
                (max_slots,) + leaf.shape, leaf.dtype
            ),
            cache_avals,
        )
        return jax.jit(
            build, out_shardings=self._kv_shardings(grid_avals)
        )()

    def insert_slot(self, slot_cache, slot: int, row_cache):
        """Splice a freshly prefilled batch-1 cache (cache_index
        included) into slot `slot`. The grid is donated: HBM updates in
        place. The old grid reference is consumed — use the return."""
        return self._insert_jit(
            slot_cache, row_cache, jnp.asarray(slot, jnp.int32)
        )

    def evict_slot(self, slot_cache, slot: int):
        """Zero slot `slot` (KV content and cache_index), returning the
        donated grid. Freeing is host-side bookkeeping — this exists so
        a retired slot's stale cache can never leak into a later
        admission path that skips prefill (slot_prefill_len == 0)."""
        return self._evict_jit(slot_cache, jnp.asarray(slot, jnp.int32))

    def step(
        self,
        params,
        slot_cache,
        tokens,
        rngs,
        sample_mask,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
    ):
        """Advance every slot of the grid one token in ONE compiled
        program (build_step_fn). Compiled once per (grid size, sampling
        config, params fingerprint); the KV grid and the per-slot rng
        buffer are donated. Returns (slot_cache, emitted [S], rngs)."""
        params = self._place_params(params)
        tokens = jnp.asarray(tokens, jnp.int32)
        rngs = jnp.asarray(rngs, jnp.uint32)
        sample_mask = jnp.asarray(sample_mask, bool)
        fp = self._params_fingerprint(params)
        slots = int(tokens.shape[0])
        step_key = (slots, float(temperature), top_k, top_p, fp)
        step_fn = build_step_fn(self.model, temperature, top_k, top_p)
        step_args = (params, slot_cache, tokens, rngs, sample_mask)
        out_shardings = None
        if self.mesh is not None:
            out_shardings = (
                self._shardings_of(slot_cache), self._rep_sharding,
                self._rep_sharding,
            )
        compiled = self._compiled(
            self._step, step_key, "step",
            lambda: self._jit(
                step_fn, step_args, donate=(1, 3),
                out_shardings=out_shardings,
            ).lower(*step_args).compile(),
        )
        with telemetry.span("decode_engine/step", slots=slots):
            return compiled(*step_args)

    def spec_step(
        self,
        params,
        slot_cache,
        tokens,
        n_known,
        eos_ids,
        rngs,
        active,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
    ):
        """Advance every slot up to W = tokens.shape[1] tokens in ONE
        compiled speculative program (build_spec_step_fn). Compiled once
        per (grid size, window width, sampling config, params
        fingerprint) — tokens / n_known / eos_ids are traced, so the
        drafts changing every tick never recompiles. The KV grid and the
        rng buffer are donated. Returns (slot_cache, emitted [S, W],
        counts [S], rngs)."""
        params = self._place_params(params)
        tokens = jnp.asarray(tokens, jnp.int32)
        n_known = jnp.asarray(n_known, jnp.int32)
        eos_ids = jnp.asarray(eos_ids, jnp.int32)
        rngs = jnp.asarray(rngs, jnp.uint32)
        active = jnp.asarray(active, bool)
        slots, width = (int(tokens.shape[0]), int(tokens.shape[1]))
        fp = self._params_fingerprint(params)
        key = ("spec", slots, width, float(temperature), top_k, top_p, fp)
        fn = build_spec_step_fn(self.model, width, temperature, top_k, top_p)
        args = (params, slot_cache, tokens, n_known, eos_ids, rngs, active)
        out_shardings = None
        if self.mesh is not None:
            out_shardings = (
                self._shardings_of(slot_cache), self._rep_sharding,
                self._rep_sharding, self._rep_sharding,
            )
        compiled = self._compiled(
            self._spec_step, key, "spec_step",
            lambda: self._jit(
                fn, args, donate=(1, 5), out_shardings=out_shardings,
            ).lower(*args).compile(),
        )
        with telemetry.span("decode_engine/spec_step", slots=slots,
                            width=width):
            return compiled(*args)

    # -- paged KV slot API ---------------------------------------------------
    #
    # The paged layout (module docstring): a global pool of fixed-size
    # KV blocks + per-slot block tables, gathered/scattered INSIDE the
    # compiled programs. The host-side free-list/refcount/prefix
    # bookkeeping lives in tf_yarn_tpu/serving/paging.py; the scheduler
    # composes both.

    def make_paged_pool(self, params, num_blocks: int, block_size: int):
        """Zeroed global KV block pool: every KV leaf of the model's
        decode cache with its seq axis split into (num_blocks,
        block_size); index leaves are elided (None) — positions travel
        as `paged_step`'s traced `lengths`. Block 0 is the reserved
        trash block (serving/paging.py). Nothing runs on the device
        except the zeros allocation."""
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is reserved), "
                f"got {num_blocks}"
            )
        params = self._place_params(params)
        row_avals = _decode_cache_aval(self.model, params)
        avals = paged_pool_avals(
            row_avals, num_blocks, block_size,
            self.model.config.max_seq_len,
        )

        def build():
            return jax.tree_util.tree_map(
                lambda aval: (None if aval is None
                              else jnp.zeros(aval.shape, aval.dtype)),
                avals, is_leaf=_is_none,
            )

        if self.mesh is None:
            return build()
        # Sharded pool: every block's kv-heads axis splits over tp, so
        # each device holds 1/tp of EVERY block (pool_partition_spec —
        # the pool shape itself cannot anchor on max_seq_len, the row
        # aval supplies the axis).
        from jax.sharding import NamedSharding

        max_seq_len = self.model.config.max_seq_len
        shardings = jax.tree_util.tree_map(
            lambda aval, row: (
                None if aval is None else NamedSharding(
                    self.mesh,
                    pool_partition_spec(
                        tuple(row.shape), max_seq_len, self.tp_degree
                    ),
                )
            ),
            avals, row_avals, is_leaf=_is_none,
        )
        return jax.jit(build, out_shardings=shardings)()

    def max_blocks_per_slot(self, block_size: int) -> int:
        """Block-table width: a slot grown to max_seq_len holds exactly
        this many blocks."""
        max_seq_len = self.model.config.max_seq_len
        if max_seq_len % block_size:
            raise ValueError(
                f"block_size={block_size} must divide "
                f"max_seq_len={max_seq_len}"
            )
        return max_seq_len // block_size

    def pack_prefill(self, pool, block_ids, row_cache, prefill_len: int,
                     block_size: int):
        """Splice a prefilled batch-1 cache's first `prefill_len`
        positions into `block_ids` (ceil(prefill_len/block_size) ids,
        traced values — one compiled program per prefill bucket). The
        pool is donated: HBM updates in place; use the return."""
        block_ids = jnp.asarray(block_ids, jnp.int32)
        n_pack = -(-prefill_len // block_size)
        if block_ids.shape != (n_pack,):
            raise ValueError(
                f"pack_prefill needs {n_pack} block ids for "
                f"prefill_len={prefill_len}, got shape {block_ids.shape}"
            )
        key = ("pack", prefill_len, block_size,
               self._tree_fingerprint(pool))
        pack_fn = build_pack_prefill_fn(self.model, block_size, prefill_len)
        args = (pool, block_ids, row_cache)
        out_shardings = self._shardings_of(pool) if self.mesh is not None \
            else None
        compiled = self._compiled(
            self._pack, key, "pack",
            lambda: self._jit(
                pack_fn, args, donate=(0,), out_shardings=out_shardings,
            ).lower(*args).compile(),
        )
        with telemetry.span("decode_engine/pack_prefill",
                            prefill=prefill_len):
            return compiled(*args)

    def extract_blocks(self, params, pool, block_ids, block_size: int):
        """Gather `block_ids` (traced (W,) values — W fixed at the
        block-table width keeps this at ONE compile key per pool
        layout) pool rows into a dense payload pytree for a bulk
        `jax.device_get`. Read-only: the pool is NOT donated. Padding
        ids should aim at the trash block; their payload rows are
        garbage the caller discards."""
        params = self._place_params(params)
        block_ids = jnp.asarray(block_ids, jnp.int32)
        width = int(block_ids.shape[0])
        key = ("extract", width, block_size, self._tree_fingerprint(pool))
        args = (pool, block_ids)

        def _build():
            # The row aval costs a whole-model eval_shape trace — only
            # pay it on the compile miss, never on the per-swap hit
            # path (a suspend must cost one gather, not one trace).
            row_aval = _decode_cache_aval(self.model, params)
            fn = build_extract_blocks_fn(self.model, row_aval)
            return self._jit(fn, args).lower(*args).compile()

        compiled = self._compiled(self._extract, key, "extract", _build)
        with telemetry.span("decode_engine/extract_blocks", blocks=width):
            return compiled(*args)

    def inject_blocks(self, params, pool, block_ids, payload,
                      block_size: int):
        """Scatter a swap payload (same pytree `extract_blocks`
        produced, host or device arrays) back into physical blocks
        `block_ids`. The pool is donated — HBM updates in place; use
        the return. Rows that must not land (prefix-cache hits, pad)
        are aimed at the trash block."""
        params = self._place_params(params)
        block_ids = jnp.asarray(block_ids, jnp.int32)
        width = int(block_ids.shape[0])
        key = ("inject", width, block_size, self._tree_fingerprint(pool))
        payload = jax.tree_util.tree_map(
            lambda leaf: None if leaf is None else jnp.asarray(leaf),
            payload, is_leaf=_is_none,
        )
        args = (pool, block_ids, payload)

        def _build():
            # Same hit-path discipline as extract_blocks: the model
            # trace behind the row aval runs once per layout, not once
            # per resume.
            row_aval = _decode_cache_aval(self.model, params)
            fn = build_inject_blocks_fn(self.model, row_aval)
            out_shardings = self._shardings_of(pool) \
                if self.mesh is not None else None
            return self._jit(
                fn, args, donate=(0,), out_shardings=out_shardings,
            ).lower(*args).compile()

        compiled = self._compiled(self._inject, key, "inject", _build)
        with telemetry.span("decode_engine/inject_blocks", blocks=width):
            return compiled(*args)

    def paged_step(
        self,
        params,
        pool,
        tables,
        lengths,
        tokens,
        rngs,
        sample_mask,
        block_size: int,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
    ):
        """Advance every slot one token against the block pool in ONE
        compiled program (build_paged_step_fn). Compiled once per (grid
        size, pool shape, block size, sampling config, params
        fingerprint); tables/lengths/tokens are traced, so per-tick
        table changes never recompile. The pool and the rng buffer are
        donated. Returns (pool, emitted [S], rngs)."""
        params = self._place_params(params)
        tables = jnp.asarray(tables, jnp.int32)
        lengths = jnp.asarray(lengths, jnp.int32)
        tokens = jnp.asarray(tokens, jnp.int32)
        rngs = jnp.asarray(rngs, jnp.uint32)
        sample_mask = jnp.asarray(sample_mask, bool)
        slots = int(tokens.shape[0])
        key = (slots, tuple(tables.shape), block_size, float(temperature),
               top_k, top_p, self._params_fingerprint(params),
               self._tree_fingerprint(pool))
        step_fn = build_paged_step_fn(
            self.model, block_size, temperature, top_k, top_p
        )
        args = (params, pool, tables, lengths, tokens, rngs, sample_mask)
        out_shardings = None
        if self.mesh is not None:
            out_shardings = (
                self._shardings_of(pool), self._rep_sharding,
                self._rep_sharding,
            )
        compiled = self._compiled(
            self._paged_step, key, "paged_step",
            lambda: self._jit(
                step_fn, args, donate=(1, 5), out_shardings=out_shardings,
            ).lower(*args).compile(),
        )
        with telemetry.span("decode_engine/paged_step", slots=slots):
            return compiled(*args)

    def paged_spec_step(
        self,
        params,
        pool,
        tables,
        lengths,
        tokens,
        n_known,
        eos_ids,
        rngs,
        active,
        block_size: int,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        decode_attention: str = "gather",
    ):
        """Advance every slot up to W = tokens.shape[1] tokens against
        the block pool in ONE compiled speculative program
        (build_paged_spec_step_fn; `decode_attention` picks the gather
        vs fused-kernel verify forward). tables / lengths / tokens /
        n_known / eos_ids are traced — per-tick changes never recompile.
        The pool and the rng buffer are donated. Returns (pool, emitted
        [S, W], counts [S], rngs)."""
        if decode_attention == "fused" and self.tp_degree > 1:
            raise ValueError(
                "decode_attention='fused' cannot run tensor-parallel "
                "yet: paged_int8_window_attention reads the whole block "
                "pool inside one pallas kernel and cannot read a "
                f"tp={self.tp_degree}-sharded pool; use "
                "decode_attention='gather' (XLA shards the gather "
                "path), or tp=1"
            )
        params = self._place_params(params)
        tables = jnp.asarray(tables, jnp.int32)
        lengths = jnp.asarray(lengths, jnp.int32)
        tokens = jnp.asarray(tokens, jnp.int32)
        n_known = jnp.asarray(n_known, jnp.int32)
        eos_ids = jnp.asarray(eos_ids, jnp.int32)
        rngs = jnp.asarray(rngs, jnp.uint32)
        active = jnp.asarray(active, bool)
        slots, width = (int(tokens.shape[0]), int(tokens.shape[1]))
        key = ("paged_spec", slots, width, tuple(tables.shape), block_size,
               decode_attention, float(temperature), top_k, top_p,
               self._params_fingerprint(params),
               self._tree_fingerprint(pool))
        fn = build_paged_spec_step_fn(
            self.model, block_size, width, temperature, top_k, top_p,
            decode_attention=decode_attention,
        )
        args = (params, pool, tables, lengths, tokens, n_known, eos_ids,
                rngs, active)
        out_shardings = None
        if self.mesh is not None:
            out_shardings = (
                self._shardings_of(pool), self._rep_sharding,
                self._rep_sharding, self._rep_sharding,
            )
        compiled = self._compiled(
            self._paged_spec_step, key, "paged_spec_step",
            lambda: self._jit(
                fn, args, donate=(1, 7), out_shardings=out_shardings,
            ).lower(*args).compile(),
        )
        with telemetry.span("decode_engine/paged_spec_step", slots=slots,
                            width=width):
            return compiled(*args)

    def _tree_fingerprint(self, tree) -> int:
        leaves = jax.tree_util.tree_leaves(tree)
        return hash(tuple(
            (tuple(leaf.shape), str(leaf.dtype)) for leaf in leaves
        ))

    # -- compiled-artifact introspection -----------------------------------
    #
    # The HLO analysis engine (tf_yarn_tpu/analysis/hlo_engine.py) audits
    # what this engine actually compiled: the cache keys prove tick-to-tick
    # host inputs stayed traced (TYA205 recompile-churn — a key that varies
    # across ticks means something that should be a traced value became a
    # static one), and the executables themselves carry the optimized HLO
    # (collective census, donation aliasing).

    def _program_caches(self) -> Dict[str, Dict[tuple, Any]]:
        return {
            "prefill": self._prefill,
            "decode": self._decode,
            "step": self._step,
            "paged_step": self._paged_step,
            "pack": self._pack,
            "spec_step": self._spec_step,
            "paged_spec_step": self._paged_spec_step,
            "extract": self._extract,
            "inject": self._inject,
        }

    def program_keys(self) -> Dict[str, List[tuple]]:
        """Every compile-cache key per program kind, in insertion order.
        One key per kind across a serving run is the recompile-free
        contract the paged/spec tick programs promise (tables / lengths /
        tokens are traced); `stats` carries the matching
        `{kind}_compiles` counters."""
        with self._lock:
            return {
                kind: list(cache)
                for kind, cache in self._program_caches().items()
            }

    def compiled_programs(self) -> Dict[str, Dict[tuple, Any]]:
        """The compiled executables per kind keyed exactly like
        `program_keys` — each exposes the optimized HLO via
        `.as_text()`, which is what the TYA2xx compiled-artifact rules
        read (input_output_alias map, collective ops)."""
        with self._lock:
            return {
                kind: dict(cache)
                for kind, cache in self._program_caches().items()
            }

    # -- the public entry point --------------------------------------------

    def generate(
        self,
        params,
        prompt_tokens,
        max_new_tokens: int,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        seed: int = 0,
        eos_token: Optional[int] = None,
    ):
        """Drop-in `generate`: [B, P] -> [B, P + max_new_tokens] int32."""
        prompt = jnp.asarray(prompt_tokens, jnp.int32)
        b, prompt_len = prompt.shape
        cfg = self.model.config
        if prompt_len + max_new_tokens > cfg.max_seq_len:
            raise ValueError(
                f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds config.max_seq_len ({cfg.max_seq_len}) — the KV "
                "cache size"
            )
        if max_new_tokens == 0:
            return prompt
        max_batch = self.batch_buckets[-1] if self.batch_buckets else None
        if max_batch is not None and b > max_batch:
            # Chunk through the largest bucket instead of compiling a
            # one-off unbucketed program for every oversized batch size.
            # Greedy outputs are identical either way (rows are
            # independent); at temperature > 0 each chunk draws from its
            # own seed-`seed` chain, matching a direct call on that
            # chunk — the same documented caveat batch padding already
            # carries (categorical noise is shaped by the device batch).
            with self._lock:
                self.stats["oversize_batch_chunks"] += 1
            telemetry.get_registry().counter(
                "decode_engine/oversize_batch_chunks"
            ).inc()
            _logger.info(
                "decode-engine: batch %d exceeds largest bucket %d — "
                "chunking into %d calls", b, max_batch,
                -(-b // max_batch),
            )
            chunks = [
                self.generate(
                    params, prompt[i:i + max_batch], max_new_tokens,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    seed=seed, eos_token=eos_token,
                )
                for i in range(0, b, max_batch)
            ]
            return jnp.concatenate(chunks, axis=0)
        params = self._place_params(params)
        fp = self._params_fingerprint(params)
        with self._lock:
            self.stats["calls"] += 1
        telemetry.get_registry().counter("decode_engine/calls").inc()

        b_bucket, f = self.select_buckets(b, prompt_len)
        if b_bucket != (_ceil_bucket(b, self.batch_buckets) or -1) \
                or f != (_floor_bucket(prompt_len, self.prompt_buckets) or -1):
            with self._lock:
                self.stats["unbucketed_shapes"] += 1
            telemetry.get_registry().counter(
                "decode_engine/unbucketed_shapes"
            ).inc()
            _logger.info(
                "decode-engine: shape (B=%d, P=%d) outside the bucket grid "
                "— exact-shape compile", b, prompt_len,
            )
        if b_bucket > b:
            # Pad rows participate in every device op and are sliced
            # away at the end; repeating a real row keeps them on the
            # same numeric path as genuine inputs.
            pad = jnp.broadcast_to(prompt[-1:], (b_bucket - b, prompt_len))
            prompt_padded = jnp.concatenate([prompt, pad], axis=0)
        else:
            prompt_padded = prompt
        rest_len = prompt_len - f
        has_rest = rest_len > 0
        has_eos = eos_token is not None

        cache, last_logits = self._compiled_prefill(
            params, prompt_padded[:, :f], fp
        )

        t_max = -(-max_new_tokens // self.token_bucket) * self.token_bucket
        out0 = jnp.full(
            (b_bucket, t_max),
            eos_token if has_eos else 0,
            jnp.int32,
        )
        rng = jax.random.PRNGKey(seed)
        num_new = jnp.asarray(max_new_tokens, jnp.int32)
        eos_id = jnp.asarray(eos_token if has_eos else -1, jnp.int32)

        decode_key = (b_bucket, t_max, has_rest, has_eos, float(temperature),
                      top_k, top_p, fp)
        if has_rest:
            rest = jnp.zeros((b_bucket, self._rest_width), jnp.int32)
            rest = jax.lax.dynamic_update_slice(
                rest, prompt_padded[:, f:], (0, 0)
            )
            decode_args = (params, cache, rest,
                           jnp.asarray(rest_len, jnp.int32), num_new, rng,
                           eos_id, out0)
            donate = (1, 7)
        else:
            decode_args = (params, cache, last_logits, num_new, rng, eos_id,
                           out0)
            donate = (1, 6)
        decode_fn = build_decode_fn(
            self.model, temperature, top_k, top_p, has_eos, has_rest
        )
        decode_out_shardings = None
        if self.mesh is not None:
            decode_out_shardings = (
                self._rep_sharding, self._shardings_of(cache),
            )
        compiled_decode = self._compiled(
            self._decode, decode_key, "decode",
            lambda: self._jit(
                decode_fn, decode_args, donate=donate,
                out_shardings=decode_out_shardings,
            ).lower(*decode_args).compile(),
        )
        # The returned final cache exists only to give the donated input
        # cache an output to alias; dropping it frees the HBM.
        with telemetry.span("decode_engine/decode", batch=b_bucket):
            out, _cache = compiled_decode(*decode_args)
        generated = out[:b, :max_new_tokens]
        return jnp.concatenate([prompt, generated], axis=1)


# --------------------------------------------------------------------------
# Module-level engine registry: `generate()` routes every caller through
# a shared engine per model, so repeated calls — including the thin
# compatibility wrapper's — hit the compile cache.
# --------------------------------------------------------------------------

_ENGINES: Dict[Any, DecodeEngine] = {}
_ENGINES_LOCK = threading.Lock()


def get_engine(model, mesh=None) -> DecodeEngine:
    """The shared engine for `model` (flax modules hash by structure, so
    equal configs share one engine; unhashable models fall back to
    identity). `mesh` keys the registry too — a tensor-parallel engine
    and a single-device engine for the same model are distinct programs
    and must not share compile caches."""
    try:
        key = (model, mesh)
        hash(key)
    except TypeError:
        key = (id(model), mesh)
    with _ENGINES_LOCK:
        engine = _ENGINES.get(key)
        if engine is None:
            engine = _ENGINES[key] = DecodeEngine(model, mesh=mesh)
        return engine


def clear_engines() -> None:
    """Drop every cached engine (tests; frees compiled executables)."""
    with _ENGINES_LOCK:
        _ENGINES.clear()
