"""Persistent compiled decode engine: cached jit + on-device EOS loop.

`models.generate.generate` paid three per-call taxes: a *fresh* jitted
step closure per call (its compile cache died with the call), one host
round-trip per generated token (`bool(finished.all())`), and a compiled
shape per (batch, prompt-len) a caller happened to send. `DecodeEngine`
removes all three:

* **Cached AOT compiles.** Prefill is lowered+compiled once per
  (batch-bucket, prompt-bucket) and the decode loop once per
  batch-bucket; executables live on the engine and are reused across
  batches. Every compile is logged with its key so recompile storms are
  visible, and `stats` counts compiles vs cache hits.

* **On-device decode loop.** The whole token loop is ONE
  `jax.lax.while_loop` inside ONE compiled program: sampling, KV-cache
  append, EOS-finished masking, and the all-finished early-exit
  condition are all traced. Zero device→host transfers per token — the
  only sync is the caller reading the finished sequences. The KV cache
  and the output token buffer are donated (`donate_argnums`), so each
  step updates HBM in place instead of double-buffering the cache.

* **Shape bucketing.** Batch is padded UP to the next configured bucket
  (pad rows are sliced back out). Prompt length is floor-bucketed:
  prefill runs at the largest bucket <= P and the remaining P-F prompt
  tokens are teacher-forced through the device loop (their K/V appended,
  their sampled tokens discarded). Unlike right-padding the prompt, the
  replay is *exact* — cache contents, RoPE positions, and the RNG stream
  match the unbucketed path, so outputs are identical to
  `generate_legacy` — while recompiles stay bounded by the bucket grid.

The loop-trip-count inputs (actual replay length, max_new_tokens, the
eos id, the PRNG seed) are traced scalars, so they never force a
recompile; only shapes and the sampling configuration (temperature /
top_k / top_p are baked into the traced program) key the cache.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from tf_yarn_tpu import telemetry
from tf_yarn_tpu.models.generate import _sample

_logger = logging.getLogger(__name__)

# Bucket grids: batch is ceil-padded, prompt is floor-bucketed (see
# module docstring). Sizes outside the grid fall back to exact-shape
# compiles, logged as unbucketed.
DEFAULT_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64)
DEFAULT_PROMPT_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)
# The output token buffer is sized in multiples of this, so max_new_tokens
# only recompiles when it crosses a multiple, not on every value.
DEFAULT_TOKEN_BUCKET = 64


def build_prefill_fn(model):
    """(params, prompt [B, F]) -> (cache, last-position logits [B, V])."""

    def prefill(params, prompt):
        logits, state = model.apply(
            params, prompt, decode=True, mutable=["cache"]
        )
        return state["cache"], logits[:, -1]

    return prefill


def build_decode_fn(model, temperature: float, top_k: Optional[int],
                    top_p: Optional[float], has_eos: bool, has_rest: bool):
    """The single-program decode loop, shared by the engine and the
    analysis jaxpr entry points.

    has_rest=True signature:
        fn(params, cache, rest, rest_len, num_new, rng, eos_id, out)
    has_rest=False signature (prompt hit a bucket exactly — the first
    token is sampled from the prefill logits, outside the loop):
        fn(params, cache, last_logits, num_new, rng, eos_id, out)

    `rest_len`, `num_new`, `eos_id` are traced scalars; `out` is the
    preallocated token buffer [B, T] (pre-filled with eos when has_eos,
    so the early-exit tail is already correct). Returns (filled buffer,
    final cache): the caller donates `cache` and `out`, and returning
    the cache gives XLA the output to alias the donated input against —
    the loop carry then updates the prefill cache's HBM in place instead
    of copying it into the program.

    Loop-step semantics mirror generate_legacy exactly, including the
    RNG split chain: replay steps (t < rest_len-1) consume no RNG; the
    step at t == rest_len-1 samples the first generated token with the
    first split (generate_legacy's prefill sample); each later step
    advances the chain once.
    """

    def step_apply(params, cache, token):
        logits, state = model.apply(
            {**params, "cache": cache}, token[:, None], decode=True,
            mutable=["cache"],
        )
        return state["cache"], logits[:, -1]

    def make_loop(params, cache, rest, r, rng, eos_id, out,
                  first_emitted, total):
        w = rest.shape[1] if has_rest else 1
        t_max = out.shape[1]

        def cond(carry):
            _cache, cur, _rng, finished, t, _out = carry
            alive = t < total
            if has_eos:
                # cur is only an emitted token once generation started;
                # during replay the exit check must stay off.
                done = jnp.all(finished | (cur == eos_id))
                alive = alive & ((t < r) | ~done)
            return alive

        def body(carry):
            cache, cur, rng, finished, t, out = carry
            if has_rest:
                col = jax.lax.dynamic_slice_in_dim(
                    rest, jnp.clip(t, 0, w - 1), 1, axis=1
                )[:, 0]
                token_in = jnp.where(t < r, col, cur)
            else:
                token_in = cur
            cache, logits = step_apply(params, cache, token_in)
            # Replay steps before the last consume no RNG and emit
            # nothing — the split chain stays aligned with the
            # unbucketed path's one-split-per-sample.
            do_sample = t >= r - 1
            next_rng, sample_key = jax.random.split(rng)
            rng = jnp.where(do_sample, next_rng, rng)
            sampled = _sample(logits, sample_key, temperature, top_k, top_p)
            if has_eos:
                # Generation steps after the first: a row that already
                # emitted eos keeps emitting eos.
                finished = jnp.where(
                    t >= r, finished | (cur == eos_id), finished
                )
                emit = jnp.where(finished, eos_id, sampled)
            else:
                emit = sampled
            cur = jnp.where(do_sample, emit, cur)
            k = jnp.clip(t - r + 1, 0, t_max - 1)
            written = jax.lax.dynamic_update_slice(
                out, emit[:, None].astype(out.dtype), (0, k)
            )
            out = jnp.where(do_sample, written, out)
            return cache, cur, rng, finished, t + 1, out

        b = out.shape[0]
        finished0 = jnp.zeros((b,), bool)
        carry = (cache, first_emitted, rng, finished0,
                 jnp.asarray(0, jnp.int32), out)
        cache, _cur, _rng, _fin, _t, out = jax.lax.while_loop(
            cond, body, carry
        )
        return out, cache

    if has_rest:
        def decode(params, cache, rest, rest_len, num_new, rng, eos_id, out):
            b = out.shape[0]
            cur0 = jnp.zeros((b,), jnp.int32)
            total = rest_len + num_new - 1
            return make_loop(params, cache, rest, rest_len, rng,
                             eos_id, out, cur0, total)
    else:
        def decode(params, cache, last_logits, num_new, rng, eos_id, out):
            rng, first_key = jax.random.split(rng)
            first = _sample(last_logits, first_key, temperature, top_k, top_p)
            out = jax.lax.dynamic_update_slice(
                out, first[:, None].astype(out.dtype), (0, 0)
            )
            zero = jnp.asarray(0, jnp.int32)
            return make_loop(params, cache, None, zero, rng,
                             eos_id, out, first, num_new - 1)

    return decode


def _ceil_bucket(value: int, buckets: Tuple[int, ...]) -> Optional[int]:
    for b in sorted(buckets):
        if b >= value:
            return b
    return None


def _floor_bucket(value: int, buckets: Tuple[int, ...]) -> Optional[int]:
    best = None
    for b in sorted(buckets):
        if b <= value:
            best = b
    return best


class DecodeEngine:
    """Persistent compiled generation for one model (see module docstring).

    Thread-safe for the compile cache; concurrent `generate` calls are
    serialized only while looking up / inserting executables.
    """

    def __init__(
        self,
        model,
        batch_buckets: Tuple[int, ...] = DEFAULT_BATCH_BUCKETS,
        prompt_buckets: Tuple[int, ...] = DEFAULT_PROMPT_BUCKETS,
        token_bucket: int = DEFAULT_TOKEN_BUCKET,
    ):
        if token_bucket < 1:
            raise ValueError(f"token_bucket must be >= 1, got {token_bucket}")
        self.model = model
        self.batch_buckets = tuple(sorted(set(batch_buckets)))
        self.prompt_buckets = tuple(sorted(set(prompt_buckets)))
        self.token_bucket = int(token_bucket)
        # One rest-buffer width for every bucketed prompt interval keeps
        # the decode program shared across prompt buckets: the replay
        # remainder is at most the widest gap in the grid.
        gaps = [b2 - b1 for b1, b2 in zip(self.prompt_buckets,
                                          self.prompt_buckets[1:])]
        self._rest_width = max(gaps) if gaps else 1
        self._prefill: Dict[tuple, Any] = {}
        self._decode: Dict[tuple, Any] = {}
        self._lock = threading.Lock()
        self.stats = {
            "calls": 0,
            "prefill_compiles": 0,
            "decode_compiles": 0,
            "prefill_cache_hits": 0,
            "decode_cache_hits": 0,
            "unbucketed_shapes": 0,
        }

    # -- bucket selection --------------------------------------------------

    def select_buckets(self, batch: int, prompt_len: int) -> Tuple[int, int]:
        """(padded batch, prefill length) for an incoming [B, P] batch.

        Batch pads UP (extra rows are discarded); prompt floors DOWN
        (the remainder replays through the decode loop). Out-of-grid
        sizes return themselves — an exact-shape, logged compile.
        """
        b_bucket = _ceil_bucket(batch, self.batch_buckets) or batch
        p_bucket = _floor_bucket(prompt_len, self.prompt_buckets) or prompt_len
        # A remainder wider than the rest buffer (prompt beyond the
        # grid) cannot replay — prefill the exact length instead.
        if prompt_len - p_bucket > self._rest_width:
            p_bucket = prompt_len
        return b_bucket, p_bucket

    def _params_fingerprint(self, params) -> int:
        leaves, treedef = jax.tree_util.tree_flatten(params)
        return hash((treedef, tuple(
            (tuple(leaf.shape), str(leaf.dtype)) for leaf in leaves
        )))

    # -- compile cache -----------------------------------------------------

    def _compiled(self, cache_dict, key, stat_prefix, build):
        registry = telemetry.get_registry()
        with self._lock:
            compiled = cache_dict.get(key)
            if compiled is not None:
                self.stats[f"{stat_prefix}_cache_hits"] += 1
                registry.counter(
                    "decode_engine/cache_hits", kind=stat_prefix
                ).inc()
                return compiled
        # Compile outside the lock (slow); a racing duplicate compile is
        # harmless — last writer wins, both executables are equivalent.
        with telemetry.span(
            "decode_engine/compile", kind=stat_prefix, key=str(key)
        ) as sp:
            compiled = build()
        registry.counter("decode_engine/compiles", kind=stat_prefix).inc()
        registry.histogram(
            "decode_engine/compile_seconds", kind=stat_prefix
        ).observe(sp.duration)
        with self._lock:
            cache_dict[key] = compiled
            self.stats[f"{stat_prefix}_compiles"] += 1
            _logger.info(
                "decode-engine compiled %s program for key=%s "
                "(%d %s compiles, %d cached)",
                stat_prefix, key, self.stats[f"{stat_prefix}_compiles"],
                stat_prefix, len(cache_dict),
            )
        return compiled

    # -- the public entry point --------------------------------------------

    def generate(
        self,
        params,
        prompt_tokens,
        max_new_tokens: int,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        seed: int = 0,
        eos_token: Optional[int] = None,
    ):
        """Drop-in `generate`: [B, P] -> [B, P + max_new_tokens] int32."""
        prompt = jnp.asarray(prompt_tokens, jnp.int32)
        b, prompt_len = prompt.shape
        cfg = self.model.config
        if prompt_len + max_new_tokens > cfg.max_seq_len:
            raise ValueError(
                f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds config.max_seq_len ({cfg.max_seq_len}) — the KV "
                "cache size"
            )
        if max_new_tokens == 0:
            return prompt
        params = jax.tree_util.tree_map(jnp.asarray, params)
        fp = self._params_fingerprint(params)
        with self._lock:
            self.stats["calls"] += 1
        telemetry.get_registry().counter("decode_engine/calls").inc()

        b_bucket, f = self.select_buckets(b, prompt_len)
        if b_bucket != (_ceil_bucket(b, self.batch_buckets) or -1) \
                or f != (_floor_bucket(prompt_len, self.prompt_buckets) or -1):
            with self._lock:
                self.stats["unbucketed_shapes"] += 1
            telemetry.get_registry().counter(
                "decode_engine/unbucketed_shapes"
            ).inc()
            _logger.info(
                "decode-engine: shape (B=%d, P=%d) outside the bucket grid "
                "— exact-shape compile", b, prompt_len,
            )
        if b_bucket > b:
            # Pad rows participate in every device op and are sliced
            # away at the end; repeating a real row keeps them on the
            # same numeric path as genuine inputs.
            pad = jnp.broadcast_to(prompt[-1:], (b_bucket - b, prompt_len))
            prompt_padded = jnp.concatenate([prompt, pad], axis=0)
        else:
            prompt_padded = prompt
        rest_len = prompt_len - f
        has_rest = rest_len > 0
        has_eos = eos_token is not None

        prefill_key = (b_bucket, f, fp)
        prefill_fn = build_prefill_fn(self.model)
        prefill_args = (params, prompt_padded[:, :f])
        compiled_prefill = self._compiled(
            self._prefill, prefill_key, "prefill",
            lambda: jax.jit(prefill_fn).lower(*prefill_args).compile(),
        )
        # Dispatch-side spans: async device futures, so these time the
        # enqueue (host cost), not the device compute — the XLA profiler
        # owns the device side.
        with telemetry.span(
            "decode_engine/prefill", batch=b_bucket, prompt=f
        ):
            cache, last_logits = compiled_prefill(*prefill_args)

        t_max = -(-max_new_tokens // self.token_bucket) * self.token_bucket
        out0 = jnp.full(
            (b_bucket, t_max),
            eos_token if has_eos else 0,
            jnp.int32,
        )
        rng = jax.random.PRNGKey(seed)
        num_new = jnp.asarray(max_new_tokens, jnp.int32)
        eos_id = jnp.asarray(eos_token if has_eos else -1, jnp.int32)

        decode_key = (b_bucket, t_max, has_rest, has_eos, float(temperature),
                      top_k, top_p, fp)
        if has_rest:
            rest = jnp.zeros((b_bucket, self._rest_width), jnp.int32)
            rest = jax.lax.dynamic_update_slice(
                rest, prompt_padded[:, f:], (0, 0)
            )
            decode_args = (params, cache, rest,
                           jnp.asarray(rest_len, jnp.int32), num_new, rng,
                           eos_id, out0)
            donate = (1, 7)
        else:
            decode_args = (params, cache, last_logits, num_new, rng, eos_id,
                           out0)
            donate = (1, 6)
        decode_fn = build_decode_fn(
            self.model, temperature, top_k, top_p, has_eos, has_rest
        )
        compiled_decode = self._compiled(
            self._decode, decode_key, "decode",
            lambda: jax.jit(decode_fn, donate_argnums=donate)
            .lower(*decode_args).compile(),
        )
        # The returned final cache exists only to give the donated input
        # cache an output to alias; dropping it frees the HBM.
        with telemetry.span("decode_engine/decode", batch=b_bucket):
            out, _cache = compiled_decode(*decode_args)
        generated = out[:b, :max_new_tokens]
        return jnp.concatenate([prompt, generated], axis=1)


# --------------------------------------------------------------------------
# Module-level engine registry: `generate()` routes every caller through
# a shared engine per model, so repeated calls — including the thin
# compatibility wrapper's — hit the compile cache.
# --------------------------------------------------------------------------

_ENGINES: Dict[Any, DecodeEngine] = {}
_ENGINES_LOCK = threading.Lock()


def get_engine(model) -> DecodeEngine:
    """The shared engine for `model` (flax modules hash by structure, so
    equal configs share one engine; unhashable models fall back to
    identity)."""
    try:
        key = model
        hash(key)
    except TypeError:
        key = id(model)
    with _ENGINES_LOCK:
        engine = _ENGINES.get(key)
        if engine is None:
            engine = _ENGINES[key] = DecodeEngine(model)
        return engine


def clear_engines() -> None:
    """Drop every cached engine (tests; frees compiled executables)."""
    with _ENGINES_LOCK:
        _ENGINES.clear()
