"""ResNet-50 — BASELINE.json config 4 (the reference's PyTorch CIFAR/
ImageNet example family, reference: examples/pytorch/pytorch_example.py).

TPU-first choices: NHWC layout (XLA's native conv layout on TPU),
bf16 compute, and GroupNorm instead of BatchNorm — GroupNorm carries no
cross-step running statistics, so the train step stays a pure function
(no mutable collections, no cross-replica stat sync) and compiles to one
clean XLA program. Convs are MXU-bound just like matmuls.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64
    num_groups: int = 32
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # "conv": the classic 7x7-stride-2 conv + 3x3 maxpool stem.
    # "space_to_depth": 4x4 space-to-depth then a 2x2 conv — the MLPerf-
    # style TPU stem. The classic stem feeds the MXU a 3-input-channel
    # conv (<=3/128 lane fill): ~6% of the model's FLOPs at a few percent
    # efficiency, enough to cap whole-model MFU (docs/ResNetMFU.md).
    # s2d repacks 4x4 pixel blocks into 48 channels so the first conv
    # fills the systolic array; same 56x56 output grid and stride as
    # conv7x7s2 + pool3x3s2 (receptive field 8x8 vs the classic 11x11 —
    # an architecture variant, not a reparametrization). Requires H, W
    # divisible by 4.
    stem: str = "conv"

    # Fused pallas GroupNorm (ops/groupnorm.py): one HBM round-trip per
    # norm instead of XLA's separate stats + normalize passes — targets
    # docs/ResNetMFU.md hypothesis 2. Param names match nn.GroupNorm, so
    # checkpoints swap freely between fused and unfused.
    fused_norms: bool = False

    def __post_init__(self):
        if self.stem not in ("conv", "space_to_depth"):
            raise ValueError(
                f"stem must be 'conv' or 'space_to_depth', got {self.stem!r}")

    @classmethod
    def resnet50(cls, **overrides) -> "ResNetConfig":
        return cls(**overrides)

    @classmethod
    def tiny(cls, **overrides) -> "ResNetConfig":
        defaults = dict(stage_sizes=(1, 1), num_classes=10, width=8, num_groups=4)
        defaults.update(overrides)
        return cls(**defaults)


class GroupNormOp(nn.Module):
    """GroupNorm with the same param names/shapes as nn.GroupNorm,
    routable through the fused pallas kernel (config.fused_norms)."""

    num_groups: int
    config: ResNetConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        from tf_yarn_tpu.ops import groupnorm as gn_ops

        c = x.shape[-1]
        scale = self.param(
            "scale", nn.initializers.ones, (c,), cfg.param_dtype)
        bias = self.param(
            "bias", nn.initializers.zeros, (c,), cfg.param_dtype)
        fn = gn_ops.groupnorm if cfg.fused_norms else gn_ops.groupnorm_reference
        return fn(x, scale, bias, self.num_groups, eps=1e-6).astype(cfg.dtype)


class Bottleneck(nn.Module):
    filters: int
    strides: int
    config: ResNetConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        conv = partial(nn.Conv, use_bias=False, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype)
        norm = partial(GroupNormOp, num_groups=min(cfg.num_groups, self.filters),
                       config=cfg)
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = nn.relu(norm(name="norm1")(y))
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                 name="conv2")(y)
        y = nn.relu(norm(name="norm2")(y))
        y = conv(self.filters * 4, (1, 1), name="conv3")(y)
        y = norm(num_groups=min(cfg.num_groups, self.filters * 4), name="norm3")(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.strides, self.strides), name="proj")(x)
            residual = norm(num_groups=min(cfg.num_groups, self.filters * 4),
                            name="proj_norm")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """images [B, H, W, C] -> logits [B, num_classes]."""

    config: ResNetConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        # deterministic accepted for loss-contract uniformity (no dropout).
        cfg = self.config
        x = x.astype(cfg.dtype)
        if cfg.stem == "space_to_depth":
            # [B, H, W, 3] -> [B, H/4, W/4, 48]: 4x4 pixel blocks become
            # channels, so the stem conv reads 48 input channels instead
            # of 3 and the MXU's input lanes actually fill. einops-style
            # rearrange via reshape/transpose; XLA lowers this to a copy.
            b, h, w, c = x.shape
            if h % 4 or w % 4:
                raise ValueError(
                    f"space_to_depth stem needs H, W divisible by 4, got "
                    f"{h}x{w}; pad/crop the input or use stem='conv'")
            x = x.reshape(b, h // 4, 4, w // 4, 4, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 4, w // 4, 16 * c)
            x = nn.Conv(cfg.width, (2, 2), use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="stem")(x)
            x = nn.relu(GroupNormOp(
                num_groups=min(cfg.num_groups, cfg.width), config=cfg,
                name="stem_norm")(x))
        else:
            x = nn.Conv(cfg.width, (7, 7), strides=(2, 2), use_bias=False,
                        dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                        name="stem")(x)
            x = nn.relu(GroupNormOp(num_groups=min(cfg.num_groups, cfg.width),
                                    config=cfg, name="stem_norm")(x))
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(cfg.stage_sizes):
            for block in range(n_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = Bottleneck(cfg.width * 2**stage, strides, cfg,
                               name=f"stage{stage}_block{block}")(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(cfg.num_classes, dtype=jnp.float32,
                     param_dtype=cfg.param_dtype, name="head")(x)
        return x


def make_experiment(
    config: Optional[ResNetConfig] = None,
    model_dir: Optional[str] = None,
    train_steps: int = 100,
    batch_size: int = 128,
    image_size: int = 224,
    learning_rate: float = 0.1,
    mesh_spec=None,
    input_fn=None,
    **train_param_overrides,
):
    import numpy as np
    import optax

    from tf_yarn_tpu.experiment import JaxExperiment, TrainParams
    from tf_yarn_tpu.models import common

    config = config or ResNetConfig.resnet50()
    model = ResNet(config)

    def synthetic():
        rng = np.random.RandomState(0)
        while True:
            yield {
                "x": rng.randn(batch_size, image_size, image_size, 3).astype(
                    np.float32
                ),
                "y": rng.randint(0, config.num_classes, batch_size).astype(np.int32),
            }

    defaults = dict(train_steps=train_steps, log_every_steps=max(1, train_steps // 10))
    defaults.update(train_param_overrides)
    return JaxExperiment(
        model=model,
        optimizer=optax.sgd(learning_rate, momentum=0.9),
        loss_fn=common.classification_loss,
        train_input_fn=input_fn or synthetic,
        train_params=TrainParams(**defaults),
        model_dir=model_dir,
        init_fn=lambda rng, batch: model.init(rng, batch["x"]),
        mesh_spec=mesh_spec,
    )
