"""DLRM — deep CTR model for the reference's Criteo-clicks domain.

The reference's click-prediction surface is the linear estimator
(reference: examples/linear_classifier_example.py:33-79, served by
ParameterServerStrategy so the weight table can exceed one host); this is
the deep extension of the same workload — categorical embeddings, a
bottom MLP over dense features, pairwise feature interaction, and a top
MLP — built TPU-first:

* **One stacked embedding table.** All categorical tables concatenate
  into a single ``[sum(table_sizes), embed_dim]`` param sharded over the
  fsdp axis (the PS replacement, SURVEY.md §2.4): per-feature offsets are
  baked in at trace time and one fused gather fetches every feature's
  row. No per-table gathers, no parameter servers — lookups of remote
  shards ride ICI collectives inserted by XLA.
* **Interaction as one batched matmul.** Pairwise dots between feature
  embeddings are ``einsum('bfd,bgd->bfg')`` — an MXU-shaped batched
  matmul — with the static upper-triangle gathered afterwards, instead of
  a scalar loop over pairs.
* **bf16 compute, f32 params/loss**, static shapes throughout.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class DLRMConfig:
    # Criteo clicks: 26 categorical + 13 numeric features.
    table_sizes: Tuple[int, ...] = (2**17,) * 26
    embed_dim: int = 64
    n_dense: int = 13
    bottom_mlp: Tuple[int, ...] = (512, 256)
    top_mlp: Tuple[int, ...] = (512, 256)
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def total_buckets(self) -> int:
        return sum(self.table_sizes)

    @classmethod
    def criteo(cls, **overrides) -> "DLRMConfig":
        return cls(**overrides)

    @classmethod
    def tiny(cls, **overrides) -> "DLRMConfig":
        defaults = dict(
            table_sizes=(64,) * 4, embed_dim=8, n_dense=4,
            bottom_mlp=(16,), top_mlp=(16,),
        )
        defaults.update(overrides)
        return cls(**defaults)


class DLRM(nn.Module):
    """{"cat": int32 [B, F] per-table ids, "dense": [B, n_dense]} -> logit [B, 1]."""

    config: DLRMConfig

    @nn.compact
    def __call__(self, cat, dense=None, deterministic: bool = True):
        # deterministic accepted for loss-contract uniformity (no dropout
        # today; adding it to the MLPs is config-only because the loss
        # already threads the rng/flag).
        cfg = self.config
        n_tables = len(cfg.table_sizes)
        if cat.shape[-1] != n_tables:
            raise ValueError(
                f"cat has {cat.shape[-1]} features, config has {n_tables} tables"
            )
        table = self.param(
            "embedding",
            nn.with_partitioning(
                nn.initializers.normal(stddev=1.0 / np.sqrt(cfg.embed_dim)),
                ("embed", None),
            ),
            (cfg.total_buckets, cfg.embed_dim),
            cfg.param_dtype,
        )
        # Static per-table offsets into the stacked table; one gather total.
        # Ids are folded into their own table's range first (hashed-feature
        # semantics, same as linear.hash_features' mod-bucketing): without
        # it an out-of-range id would silently land in a *neighboring*
        # table's rows and train the wrong feature's embedding.
        offsets = np.concatenate(
            ([0], np.cumsum(cfg.table_sizes[:-1]))
        ).astype(np.int32)
        # Kept as numpy so they enter the trace as inline constants —
        # jnp.asarray here would emit a device_put per call, a host
        # round-trip the analysis gate (TYA103) rejects in tick programs.
        sizes = np.asarray(cfg.table_sizes, np.int32)
        ids = cat % sizes[None, :] + offsets[None, :]
        emb = table[ids].astype(cfg.dtype)  # [B, F, D]

        feats = emb
        bottom = None
        if dense is not None and cfg.n_dense:
            x = dense.astype(cfg.dtype)
            for index, width in enumerate(cfg.bottom_mlp + (cfg.embed_dim,)):
                x = nn.Dense(width, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                             name=f"bottom{index}")(x)
                x = nn.relu(x)
            bottom = x
            feats = jnp.concatenate([x[:, None, :], emb], axis=1)  # [B, F+1, D]

        # Pairwise feature interaction on the MXU; strict upper triangle
        # (self-dots excluded, symmetric pairs deduped) via static indices.
        inter = jnp.einsum("bfd,bgd->bfg", feats, feats)
        n_feats = feats.shape[1]
        iu, ju = np.triu_indices(n_feats, k=1)
        # Flat take with a numpy index constant — the [:, iu, ju] fancy
        # form routes the index arrays through device_put at trace time
        # (TYA103 rejects that in tick programs); same gathered elements.
        pairs = jnp.take(
            inter.reshape(inter.shape[0], n_feats * n_feats),
            (iu * n_feats + ju).astype(np.int32),
            axis=1,
        )  # [B, n_pairs]

        top = jnp.concatenate([bottom, pairs], -1) if bottom is not None else pairs
        for index, width in enumerate(self.config.top_mlp):
            top = nn.Dense(width, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                           name=f"top{index}")(top)
            top = nn.relu(top)
        return nn.Dense(1, dtype=jnp.float32, param_dtype=cfg.param_dtype,
                        name="head")(top)


def dlrm_loss(model, params, batch, rng, train=True):
    """Sigmoid cross-entropy over {"cat", "dense", "y"} batches (the
    common.binary_logistic_loss contract, with DLRM's two feature
    tensors)."""
    import optax

    logits = model.apply(
        params, batch["cat"], batch.get("dense"),
        rngs={"dropout": rng}, deterministic=not train,
    ).squeeze(-1)
    labels = batch["y"].astype(jnp.float32)
    loss = optax.sigmoid_binary_cross_entropy(logits, labels).mean()
    accuracy = jnp.mean((logits > 0) == (labels > 0.5))
    return loss, {"accuracy": accuracy}


def make_experiment(
    config: Optional[DLRMConfig] = None,
    model_dir: Optional[str] = None,
    train_steps: int = 200,
    batch_size: int = 1024,
    learning_rate: float = 1e-3,
    mesh_spec=None,
    input_fn=None,
    **train_param_overrides,
):
    import optax

    from tf_yarn_tpu.experiment import JaxExperiment, TrainParams

    config = config or DLRMConfig.criteo()
    model = DLRM(config)

    def synthetic():
        # Balanced, learnable labels: each bucket of table 0 carries a
        # fixed ±1 vote (memorizable in its embedding row), so a working
        # model separates the classes and a broken one sits at ~50% —
        # unlike rare-positive CTR labels, where all-negative already
        # scores >90% and hides breakage.
        rng = np.random.RandomState(0)
        n_tables = len(config.table_sizes)
        sizes = np.asarray(config.table_sizes)
        while True:
            cat = rng.randint(0, sizes, (batch_size, n_tables)).astype(np.int32)
            dense = rng.lognormal(0.0, 1.0, (batch_size, config.n_dense))
            y = cat[:, 0] % 2
            yield {
                "cat": cat,
                "dense": np.log1p(dense).astype(np.float32),
                "y": y.astype(np.int32),
            }

    defaults = dict(train_steps=train_steps, log_every_steps=max(1, train_steps // 10))
    defaults.update(train_param_overrides)
    return JaxExperiment(
        model=model,
        optimizer=optax.adagrad(learning_rate),
        loss_fn=dlrm_loss,
        train_input_fn=input_fn or synthetic,
        train_params=TrainParams(**defaults),
        model_dir=model_dir,
        init_fn=lambda rng, batch: model.init(rng, batch["cat"], batch.get("dense")),
        mesh_spec=mesh_spec,
    )
