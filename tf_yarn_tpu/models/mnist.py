"""MNIST-style dense classifier — BASELINE.json config 1.

The acceptance model for the minimum end-to-end slice (SURVEY.md §7.4):
a plain flax MLP with *no* sharding annotations, exercising the
unannotated-model path (FSDP inference / replication) of
tf_yarn_tpu.parallel.sharding.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import optax

from tf_yarn_tpu.experiment import JaxExperiment, TrainParams
from tf_yarn_tpu.models import common
from tf_yarn_tpu.parallel.mesh import MeshSpec


class DenseClassifier(nn.Module):
    hidden_sizes: Sequence[int] = (512, 256)
    num_classes: int = 10
    dropout_rate: float = 0.0

    @nn.compact
    def __call__(self, x, deterministic: bool = False):
        x = x.reshape((x.shape[0], -1))
        for size in self.hidden_sizes:
            x = nn.relu(nn.Dense(size)(x))
            if self.dropout_rate:
                x = nn.Dropout(self.dropout_rate, deterministic=deterministic)(x)
        return nn.Dense(self.num_classes)(x)


def make_experiment(
    model_dir: Optional[str] = None,
    train_steps: int = 200,
    batch_size: int = 128,
    feature_dim: int = 784,
    num_classes: int = 10,
    learning_rate: float = 1e-3,
    mesh_spec: Optional[MeshSpec] = None,
    input_fn=None,
    eval_input_fn=None,
    **train_param_overrides,
) -> JaxExperiment:
    model = DenseClassifier(num_classes=num_classes)
    defaults = dict(
        train_steps=train_steps,
        log_every_steps=max(1, train_steps // 10),
    )
    defaults.update(train_param_overrides)
    return JaxExperiment(
        model=model,
        optimizer=optax.adam(learning_rate),
        loss_fn=common.classification_loss,
        train_input_fn=input_fn
        or (
            lambda: common.synthetic_classification_iter(
                batch_size, feature_dim, num_classes
            )
        ),
        eval_input_fn=eval_input_fn,
        train_params=TrainParams(**defaults),
        model_dir=model_dir,
        mesh_spec=mesh_spec,
    )
