"""Vision Transformer — image classification on the encoder stack.

Extends the zoo beyond the reference's vision surface (its image path is
the opaque torch CNN of examples/pytorch/pytorch_example.py; ResNet
covers that here) with the transformer-native alternative: patchify via
one conv (stride = patch size — an MXU matmul per patch, no im2col), a
CLS token + learned position embeddings, and the *same* EncoderBlock as
BERT (models/bert.py) — one encoder implementation for both modalities,
so megatron logical names, TP/FSDP placement, LoRA, and the attention
dispatcher all apply unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp

from tf_yarn_tpu.models.bert import BertNorm, EncoderBlock, _Dense
from tf_yarn_tpu.models.transformer import EMBED, _partitioned


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    # Duck-compatible with BertConfig for EncoderBlock/_Dense reuse.
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    dropout_rate: float = 0.0
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    attention_impl: str = "xla"
    lora_rank: int = 0
    lora_alpha: float = 16.0
    # Fused pallas LayerNorm for the shared EncoderBlock + final_norm
    # (duck-compat with BertConfig.fused_norms; ops/layernorm.py).
    fused_norms: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @classmethod
    def base16(cls, **overrides) -> "ViTConfig":
        return cls(**overrides)

    @classmethod
    def tiny(cls, **overrides) -> "ViTConfig":
        defaults = dict(
            image_size=32, patch_size=8, num_classes=10, d_model=32,
            n_layers=2, n_heads=2, d_ff=64,
        )
        defaults.update(overrides)
        return cls(**defaults)


class ViT(nn.Module):
    """images [B, H, W, C] -> logits [B, num_classes]."""

    config: ViTConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        if x.shape[1] != cfg.image_size or x.shape[2] != cfg.image_size:
            raise ValueError(
                f"expected {cfg.image_size}x{cfg.image_size} images, "
                f"got {x.shape[1]}x{x.shape[2]}"
            )
        p = cfg.patch_size
        x = nn.Conv(
            cfg.d_model, (p, p), strides=(p, p), padding="VALID",
            dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="patchify",
        )(x.astype(cfg.dtype))
        b = x.shape[0]
        x = x.reshape(b, cfg.n_patches, cfg.d_model)

        cls_tok = self.param(
            "cls_token", nn.initializers.zeros_init(),
            (1, 1, cfg.d_model), cfg.param_dtype,
        )
        pos_emb = self.param(
            "position_embedding",
            _partitioned((None, EMBED))(nn.initializers.normal(stddev=0.02)),
            (cfg.n_patches + 1, cfg.d_model),
            cfg.param_dtype,
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls_tok.astype(cfg.dtype), (b, 1, cfg.d_model)), x],
            axis=1,
        )
        x = x + pos_emb.astype(cfg.dtype)[None]
        x = nn.Dropout(cfg.dropout_rate, deterministic=deterministic)(x)
        for i in range(cfg.n_layers):
            x = EncoderBlock(cfg, name=f"layer_{i}")(x, deterministic=deterministic)
        x = BertNorm(cfg, name="final_norm")(x)
        logits = _Dense(cfg.num_classes, (EMBED, None), cfg, name="head")(x[:, 0])
        return logits.astype(jnp.float32)


def make_experiment(
    config: Optional[ViTConfig] = None,
    model_dir: Optional[str] = None,
    train_steps: int = 100,
    batch_size: int = 128,
    learning_rate: float = 3e-4,
    mesh_spec=None,
    input_fn=None,
    **train_param_overrides,
):
    import numpy as np
    import optax

    from tf_yarn_tpu.experiment import JaxExperiment, TrainParams
    from tf_yarn_tpu.models import common

    config = config or ViTConfig.base16()
    model = ViT(config)

    def synthetic():
        rng = np.random.RandomState(0)
        size = config.image_size
        while True:
            yield {
                "x": rng.randn(batch_size, size, size, 3).astype(np.float32),
                "y": rng.randint(0, config.num_classes, batch_size).astype(np.int32),
            }

    defaults = dict(train_steps=train_steps, log_every_steps=max(1, train_steps // 10))
    defaults.update(train_param_overrides)
    return JaxExperiment(
        model=model,
        optimizer=optax.adamw(learning_rate, weight_decay=0.05),
        loss_fn=common.classification_loss,
        train_input_fn=input_fn or synthetic,
        train_params=TrainParams(**defaults),
        model_dir=model_dir,
        init_fn=lambda rng, batch: model.init(rng, batch["x"]),
        mesh_spec=mesh_spec,
    )
