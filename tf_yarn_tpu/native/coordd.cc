// coordd — native coordination service (KV store + blocking waits + event log).
//
// The C++ replacement for the role the skein ApplicationMaster (Java+gRPC)
// plays in the reference (SURVEY.md §2.4: control plane — KV pub/sub, app
// lifecycle; reference usage tf_yarn/event.py:13-79, client.py:633-657).
// Speaks exactly the wire protocol of the Python KVServer
// (tf_yarn_tpu/coordination/kv.py): 4-byte big-endian length frames of
// JSON; ops put/get/wait/events/keys/incr/del/ping/shutdown. The Python
// KVClient treats the two servers as drop-in replacements; the driver
// prefers this binary when built (coordination/server_factory.py).
//
// Build: make -C tf_yarn_tpu/native       (g++ -O2 -pthread, no deps)
// Run:   coordd <host> <port>
//
// Concurrency model: one thread per connection (control-plane traffic is
// sparse — tens of clients, few requests/sec), one global mutex + condvar
// guarding the store; blocking waits sleep on the condvar, so a wait costs
// no CPU and wakes exactly when a put lands.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <optional>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Minimal JSON for this protocol: flat objects with string / double / null
// values on requests; replies additionally need arrays. Full escape handling
// for the string subset Python's json.dumps (ensure_ascii=True) emits.
// ---------------------------------------------------------------------------

struct JsonValue {
  enum class Kind { Null, Str, Num, Bool } kind = Kind::Null;
  std::string str;
  double num = 0.0;
  bool boolean = false;
};

class JsonParser {
 public:
  explicit JsonParser(const std::string& text) : text_(text) {}

  // Parse one flat object {"k": v, ...}; nested containers rejected.
  bool ParseObject(std::map<std::string, JsonValue>* out) {
    SkipWs();
    if (!Consume('{')) return false;
    SkipWs();
    if (Consume('}')) return true;
    while (true) {
      SkipWs();
      std::string key;
      if (!ParseString(&key)) return false;
      SkipWs();
      if (!Consume(':')) return false;
      SkipWs();
      JsonValue value;
      if (!ParseValue(&value)) return false;
      (*out)[key] = std::move(value);
      SkipWs();
      if (Consume(',')) continue;
      return Consume('}');
    }
  }

 private:
  void SkipWs() {
    while (pos_ < text_.size() && std::isspace(static_cast<unsigned char>(text_[pos_]))) pos_++;
  }
  bool Consume(char c) {
    if (pos_ < text_.size() && text_[pos_] == c) { pos_++; return true; }
    return false;
  }
  bool ParseValue(JsonValue* out) {
    if (pos_ >= text_.size()) return false;
    char c = text_[pos_];
    if (c == '"') { out->kind = JsonValue::Kind::Str; return ParseString(&out->str); }
    if (c == 'n') { pos_ += 4; out->kind = JsonValue::Kind::Null; return true; }
    if (c == 't') { pos_ += 4; out->kind = JsonValue::Kind::Bool; out->boolean = true; return true; }
    if (c == 'f') { pos_ += 5; out->kind = JsonValue::Kind::Bool; out->boolean = false; return true; }
    // number
    size_t start = pos_;
    while (pos_ < text_.size() &&
           (std::isdigit(static_cast<unsigned char>(text_[pos_])) || text_[pos_] == '-' ||
            text_[pos_] == '+' || text_[pos_] == '.' || text_[pos_] == 'e' || text_[pos_] == 'E'))
      pos_++;
    if (pos_ == start) return false;
    out->kind = JsonValue::Kind::Num;
    out->num = std::stod(text_.substr(start, pos_ - start));
    return true;
  }
  bool ParseString(std::string* out) {
    if (!Consume('"')) return false;
    out->clear();
    while (pos_ < text_.size()) {
      char c = text_[pos_++];
      if (c == '"') return true;
      if (c != '\\') { out->push_back(c); continue; }
      if (pos_ >= text_.size()) return false;
      char esc = text_[pos_++];
      switch (esc) {
        case '"': out->push_back('"'); break;
        case '\\': out->push_back('\\'); break;
        case '/': out->push_back('/'); break;
        case 'b': out->push_back('\b'); break;
        case 'f': out->push_back('\f'); break;
        case 'n': out->push_back('\n'); break;
        case 'r': out->push_back('\r'); break;
        case 't': out->push_back('\t'); break;
        case 'u': {
          if (pos_ + 4 > text_.size()) return false;
          unsigned code = std::stoul(text_.substr(pos_, 4), nullptr, 16);
          pos_ += 4;
          // Surrogate pair (python escapes astral chars this way).
          if (code >= 0xD800 && code <= 0xDBFF && pos_ + 6 <= text_.size() &&
              text_[pos_] == '\\' && text_[pos_ + 1] == 'u') {
            unsigned low = std::stoul(text_.substr(pos_ + 2, 4), nullptr, 16);
            pos_ += 6;
            code = 0x10000 + ((code - 0xD800) << 10) + (low - 0xDC00);
          }
          // UTF-8 encode.
          if (code < 0x80) {
            out->push_back(static_cast<char>(code));
          } else if (code < 0x800) {
            out->push_back(static_cast<char>(0xC0 | (code >> 6)));
            out->push_back(static_cast<char>(0x80 | (code & 0x3F)));
          } else if (code < 0x10000) {
            out->push_back(static_cast<char>(0xE0 | (code >> 12)));
            out->push_back(static_cast<char>(0x80 | ((code >> 6) & 0x3F)));
            out->push_back(static_cast<char>(0x80 | (code & 0x3F)));
          } else {
            out->push_back(static_cast<char>(0xF0 | (code >> 18)));
            out->push_back(static_cast<char>(0x80 | ((code >> 12) & 0x3F)));
            out->push_back(static_cast<char>(0x80 | ((code >> 6) & 0x3F)));
            out->push_back(static_cast<char>(0x80 | (code & 0x3F)));
          }
          break;
        }
        default: return false;
      }
    }
    return false;
  }

  const std::string& text_;
  size_t pos_ = 0;
};

std::string JsonEscape(const std::string& in) {
  std::string out;
  out.reserve(in.size() + 8);
  for (unsigned char c : in) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out.push_back(static_cast<char>(c));  // raw UTF-8 passes through
        }
    }
  }
  return out;
}

// ---------------------------------------------------------------------------
// base64 (values travel base64-encoded; incr must read/write real numbers)
// ---------------------------------------------------------------------------

const char kB64Chars[] = "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";

std::string B64Encode(const std::string& in) {
  std::string out;
  out.reserve((in.size() + 2) / 3 * 4);
  for (size_t i = 0; i < in.size(); i += 3) {
    uint32_t chunk = static_cast<unsigned char>(in[i]) << 16;
    if (i + 1 < in.size()) chunk |= static_cast<unsigned char>(in[i + 1]) << 8;
    if (i + 2 < in.size()) chunk |= static_cast<unsigned char>(in[i + 2]);
    out.push_back(kB64Chars[(chunk >> 18) & 0x3F]);
    out.push_back(kB64Chars[(chunk >> 12) & 0x3F]);
    out.push_back(i + 1 < in.size() ? kB64Chars[(chunk >> 6) & 0x3F] : '=');
    out.push_back(i + 2 < in.size() ? kB64Chars[chunk & 0x3F] : '=');
  }
  return out;
}

std::string B64Decode(const std::string& in) {
  auto val = [](char c) -> int {
    if (c >= 'A' && c <= 'Z') return c - 'A';
    if (c >= 'a' && c <= 'z') return c - 'a' + 26;
    if (c >= '0' && c <= '9') return c - '0' + 52;
    if (c == '+') return 62;
    if (c == '/') return 63;
    return -1;
  };
  std::string out;
  int buffer = 0, bits = 0;
  for (char c : in) {
    int v = val(c);
    if (v < 0) continue;  // '=' padding / whitespace
    buffer = (buffer << 6) | v;
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out.push_back(static_cast<char>((buffer >> bits) & 0xFF));
    }
  }
  return out;
}

// ---------------------------------------------------------------------------
// Store
// ---------------------------------------------------------------------------

class Store {
 public:
  void Put(const std::string& key, std::string value) {
    std::lock_guard<std::mutex> lock(mu_);
    data_[key] = std::move(value);
    log_.push_back(key);
    cv_.notify_all();
  }

  std::optional<std::string> Get(const std::string& key) {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = data_.find(key);
    if (it == data_.end()) return std::nullopt;
    return it->second;
  }

  // Blocks until key exists; timeout_s < 0 means wait forever.
  std::optional<std::string> Wait(const std::string& key, double timeout_s) {
    std::unique_lock<std::mutex> lock(mu_);
    auto pred = [&] { return data_.count(key) > 0; };
    if (timeout_s < 0) {
      cv_.wait(lock, pred);
    } else if (!cv_.wait_for(lock, std::chrono::duration<double>(timeout_s), pred)) {
      return std::nullopt;
    }
    return data_[key];
  }

  std::vector<std::pair<size_t, std::string>> Events(size_t since, size_t* next) {
    std::lock_guard<std::mutex> lock(mu_);
    std::vector<std::pair<size_t, std::string>> out;
    for (size_t i = since; i < log_.size(); ++i) out.emplace_back(i, log_[i]);
    *next = log_.size();
    return out;
  }

  std::vector<std::string> Keys(const std::string& prefix) {
    std::lock_guard<std::mutex> lock(mu_);
    std::vector<std::string> out;
    for (const auto& [key, _] : data_)
      if (key.rfind(prefix, 0) == 0) out.push_back(key);
    return out;  // std::map iterates sorted
  }

  // Values are stored as the base64 text the protocol carries; incr
  // decodes the decimal inside, bumps it, re-encodes.
  long long Incr(const std::string& key, long long amount) {
    std::lock_guard<std::mutex> lock(mu_);
    long long current = 0;
    auto it = data_.find(key);
    if (it != data_.end()) current = std::stoll(B64Decode(it->second));
    current += amount;
    data_[key] = B64Encode(std::to_string(current));
    log_.push_back(key);
    cv_.notify_all();
    return current;
  }

  void Del(const std::string& key) {
    std::lock_guard<std::mutex> lock(mu_);
    data_.erase(key);
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, std::string> data_;
  std::vector<std::string> log_;
};

// ---------------------------------------------------------------------------
// Framing + request handling
// ---------------------------------------------------------------------------

constexpr uint32_t kMaxFrame = 64u * 1024 * 1024;

bool RecvExact(int fd, char* buf, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd, buf + got, n - got, 0);
    if (r <= 0) return false;
    got += static_cast<size_t>(r);
  }
  return true;
}

bool SendFrame(int fd, const std::string& payload) {
  uint32_t len = htonl(static_cast<uint32_t>(payload.size()));
  char header[4];
  std::memcpy(header, &len, 4);
  std::string framed(header, 4);
  framed += payload;
  size_t sent = 0;
  while (sent < framed.size()) {
    ssize_t r = ::send(fd, framed.data() + sent, framed.size() - sent, 0);
    if (r <= 0) return false;
    sent += static_cast<size_t>(r);
  }
  return true;
}

std::string GetStr(const std::map<std::string, JsonValue>& obj, const std::string& key) {
  auto it = obj.find(key);
  return (it != obj.end() && it->second.kind == JsonValue::Kind::Str) ? it->second.str : "";
}

std::atomic<bool> g_shutdown{false};

std::string Handle(Store& store, const std::map<std::string, JsonValue>& req) {
  const std::string op = GetStr(req, "op");
  if (op == "put") {
    store.Put(GetStr(req, "key"), GetStr(req, "value"));
    return R"({"ok":true})";
  }
  if (op == "get") {
    auto value = store.Get(GetStr(req, "key"));
    if (!value) return R"({"ok":true,"value":null})";
    return std::string(R"({"ok":true,"value":")") + JsonEscape(*value) + "\"}";
  }
  if (op == "wait") {
    double timeout = -1.0;
    auto it = req.find("timeout");
    if (it != req.end() && it->second.kind == JsonValue::Kind::Num) timeout = it->second.num;
    auto value = store.Wait(GetStr(req, "key"), timeout);
    if (!value)
      return std::string(R"({"ok":false,"timeout":true,"error":"timed out waiting for )") +
             JsonEscape(GetStr(req, "key")) + "\"}";
    return std::string(R"({"ok":true,"value":")") + JsonEscape(*value) + "\"}";
  }
  if (op == "events") {
    size_t since = 0;
    auto it = req.find("since");
    if (it != req.end() && it->second.kind == JsonValue::Kind::Num)
      since = static_cast<size_t>(it->second.num);
    size_t next = 0;
    auto events = store.Events(since, &next);
    std::string out = R"({"ok":true,"events":[)";
    for (size_t i = 0; i < events.size(); ++i) {
      if (i) out += ",";
      out += "[" + std::to_string(events[i].first) + ",\"" + JsonEscape(events[i].second) + "\"]";
    }
    out += "],\"next\":" + std::to_string(next) + "}";
    return out;
  }
  if (op == "keys") {
    auto keys = store.Keys(GetStr(req, "prefix"));
    std::string out = R"({"ok":true,"keys":[)";
    for (size_t i = 0; i < keys.size(); ++i) {
      if (i) out += ",";
      out += "\"" + JsonEscape(keys[i]) + "\"";
    }
    out += "]}";
    return out;
  }
  if (op == "incr") {
    long long amount = 1;
    auto it = req.find("amount");
    if (it != req.end() && it->second.kind == JsonValue::Kind::Num)
      amount = static_cast<long long>(it->second.num);
    return R"({"ok":true,"value":)" + std::to_string(store.Incr(GetStr(req, "key"), amount)) + "}";
  }
  if (op == "del") {
    store.Del(GetStr(req, "key"));
    return R"({"ok":true})";
  }
  if (op == "ping") return R"({"ok":true,"server":"coordd"})";
  if (op == "shutdown") {
    g_shutdown = true;
    return R"({"ok":true})";
  }
  return R"({"ok":false,"error":"unknown op"})";
}

void ServeConnection(Store* store, int fd) {
  while (!g_shutdown) {
    char header[4];
    if (!RecvExact(fd, header, 4)) break;
    uint32_t len;
    std::memcpy(&len, header, 4);
    len = ntohl(len);
    if (len > kMaxFrame) break;
    std::string payload(len, '\0');
    if (!RecvExact(fd, payload.data(), len)) break;
    std::map<std::string, JsonValue> req;
    JsonParser parser(payload);
    std::string reply;
    // Malformed numbers / escapes / non-numeric incr values throw from
    // std::stoll & friends; a bad client frame must never kill the run's
    // control plane (the Python server replies ok:false the same way).
    try {
      if (!parser.ParseObject(&req)) {
        reply = R"({"ok":false,"error":"bad json"})";
      } else {
        reply = Handle(*store, req);
      }
    } catch (const std::exception& e) {
      reply = std::string(R"({"ok":false,"error":")") + JsonEscape(e.what()) + "\"}";
    }
    if (!SendFrame(fd, reply)) break;
    if (g_shutdown) break;
  }
  ::close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  const char* host = argc > 1 ? argv[1] : "127.0.0.1";
  int port = argc > 2 ? std::atoi(argv[2]) : 0;

  int listener = ::socket(AF_INET, SOCK_STREAM, 0);
  if (listener < 0) { std::perror("socket"); return 1; }
  int one = 1;
  ::setsockopt(listener, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) { std::fprintf(stderr, "bad host\n"); return 1; }
  if (::bind(listener, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    std::perror("bind");
    return 1;
  }
  if (::listen(listener, 128) != 0) { std::perror("listen"); return 1; }
  socklen_t addr_len = sizeof(addr);
  ::getsockname(listener, reinterpret_cast<sockaddr*>(&addr), &addr_len);
  std::printf("coordd listening on %s:%d\n", host, ntohs(addr.sin_port));
  std::fflush(stdout);

  Store store;
  while (!g_shutdown) {
    // Accept with a poll-ish timeout so shutdown can take effect.
    timeval tv{0, 200000};
    fd_set fds;
    FD_ZERO(&fds);
    FD_SET(listener, &fds);
    int ready = ::select(listener + 1, &fds, nullptr, nullptr, &tv);
    if (ready <= 0) continue;
    int fd = ::accept(listener, nullptr, nullptr);
    if (fd < 0) continue;
    std::thread(ServeConnection, &store, fd).detach();
  }
  ::close(listener);
  return 0;
}
