"""Replica registry: the router's live view of the serving fleet.

Discovery and liveness ride the SAME coordination-KV protocol the rest
of the runtime already speaks — no second control plane:

* **discovery** — each replica advertises its HTTP endpoint as
  ``{task}/serving_endpoint`` (event.serving_endpoint_event, generate
  replicas) or ``{task}/rank_endpoint`` (event.rank_endpoint_event,
  ranking replicas); the registry watches those keys (an explicit task
  list from the cluster spec, or a prefix scan when none is given).
  The suffix a replica advertised under IS its capability declaration:
  the registry records it as ``Replica.kind`` (``"generate"`` or
  ``"rank"``) and the router only routes a request to replicas whose
  kind matches the request path (``healthy(kind=...)``).
* **admission** — an advertised endpoint is NOT routable yet: the
  replica stays ``pending`` until its first successful ``/healthz``
  probe (a replica publishes its endpoint before the first tick has
  compiled, and routing to it would burn the router's retry budget on
  a cold socket). This closes the endpoint-published-before-healthy
  discovery race.
* **health ejection** — a replica is ejected from rotation when its
  ``/healthz`` stops answering, answers anything but ``"ok"`` (the
  preemption-drain ``"draining"`` state ejects BEFORE the socket goes
  away), or its KV heartbeat goes beat-then-silent past
  ``dead_heartbeat_s`` (the watchdog's posture, resilience/watchdog.py:
  a wedged server can still accept TCP — the heartbeat is the signal
  that the scheduler thread is alive). Ejected replicas are re-admitted
  on the first healthy probe after recovery.
* **finished is not dead** — a ``heartbeat.stopped`` tombstone or a
  ``stop`` event removes the replica from rotation as ``stopped``
  without counting an ejection, exactly like the watchdog.

KV read errors degrade the view for one refresh (previous states hold);
they never take the router down with the coordination link.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from tf_yarn_tpu import telemetry
from tf_yarn_tpu.resilience.taxonomy import classify_exception
from tf_yarn_tpu.telemetry.heartbeat import heartbeat_age

_logger = logging.getLogger(__name__)

# Replica lifecycle states.
PENDING = "pending"    # endpoint advertised, no healthy probe yet
HEALTHY = "healthy"    # in rotation
EJECTED = "ejected"    # out of rotation, re-admitted on recovery
STOPPED = "stopped"    # tombstoned / stop event: finished, not dead

DEFAULT_PROBE_TIMEOUT_S = 2.0
DEFAULT_PROBE_INTERVAL_S = 1.0

# Replica capability kinds, keyed by the KV suffix the replica
# advertised its endpoint under (the suffix IS the declaration — a
# replica that publishes rank_endpoint serves /v1/rank, nothing else).
KIND_GENERATE = "generate"
KIND_RANK = "rank"
KIND_PREFILL = "prefill"


def http_probe(endpoint: str,
               timeout: float = DEFAULT_PROBE_TIMEOUT_S) -> dict:
    """GET ``/healthz`` on a replica; the parsed JSON on HTTP 200,
    raises (ConnectionError family) otherwise. The default probe — tests
    and the bench inject fakes through the ``probe=`` seam."""
    host, _, port = endpoint.rpartition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        payload = resp.read()
        if resp.status != 200:
            raise ConnectionError(
                f"/healthz on {endpoint} answered {resp.status}"
            )
        return json.loads(payload or b"{}")
    finally:
        conn.close()


@dataclasses.dataclass
class Replica:
    """One replica as the registry sees it."""

    task: str
    endpoint: Optional[str] = None
    state: str = PENDING
    # Which request path this replica can serve ("generate" for
    # /v1/generate, "rank" for /v1/rank) — set from the KV suffix it
    # advertised under.
    kind: str = KIND_GENERATE
    # Load signals from the last probe (the /healthz payload carries the
    # scheduler occupancy) plus the router's own in-flight count — the
    # between-polls correction that keeps least-loaded from dogpiling.
    queue_depth: int = 0
    active_slots: int = 0
    inflight: int = 0
    eject_reason: Optional[str] = None
    last_probe_at: Optional[float] = None
    ejections: int = 0
    readmissions: int = 0
    relaunches: int = 0
    ever_beat: bool = False
    # Endpoint the replica was advertising when its stop tombstone was
    # observed. A later advertisement under a DIFFERENT endpoint marks
    # the tombstone as belonging to a previous incarnation — the
    # relaunched task is alive and must be probed back in.
    stopped_endpoint: Optional[str] = None
    # /healthz payload schema version; None = a legacy (pre-versioning)
    # replica that never sent one. Mixed-version fleets keep routing —
    # the version only informs readers like the monitor, never gates
    # health.
    schema_version: Optional[int] = None

    @property
    def load(self) -> int:
        return self.queue_depth + self.active_slots + self.inflight

    def snapshot(self) -> dict:
        return {
            "task": self.task,
            "endpoint": self.endpoint,
            "state": self.state,
            "kind": self.kind,
            "queue_depth": self.queue_depth,
            "active_slots": self.active_slots,
            "inflight": self.inflight,
            "eject_reason": self.eject_reason,
            "ejections": self.ejections,
            "readmissions": self.readmissions,
            "relaunches": self.relaunches,
            "schema_version": self.schema_version,
        }


class ReplicaRegistry:
    """Maintains the live replica set (module docstring).

    ``tasks=None`` discovers replicas by scanning KV keys for
    ``*/serving_endpoint`` and ``*/rank_endpoint``; a launcher passes
    the cluster's serving + rank tasks explicitly (their kind is then
    resolved from whichever endpoint key each task publishes).
    ``dead_heartbeat_s=None`` disables the heartbeat
    check (probes still govern health). ``probe_interval_s`` bounds
    probe traffic per replica; ``refresh(force=True)`` probes
    regardless (used right after an observed failure).
    """

    def __init__(
        self,
        kv,
        tasks: Optional[Sequence[str]] = None,
        *,
        probe: Callable[[str], dict] = http_probe,
        probe_interval_s: float = DEFAULT_PROBE_INTERVAL_S,
        dead_heartbeat_s: Optional[float] = None,
        clock=time.monotonic,
        wall_clock=time.time,
    ) -> None:
        self._kv = kv
        self._tasks = list(tasks) if tasks is not None else None
        self._probe = probe
        self.probe_interval_s = float(probe_interval_s)
        self.dead_heartbeat_s = dead_heartbeat_s
        self._clock = clock
        self._wall_clock = wall_clock
        self._lock = threading.RLock()
        self._replicas: Dict[str, Replica] = {}
        self._registry = telemetry.get_registry()

    # -- refresh (router poll loop; also on-demand from the router) --------

    def refresh(self, force: bool = False) -> List[Replica]:
        """One discovery + health pass; returns the healthy set."""
        with self._lock:
            for task, kind in self._discover_tasks().items():
                replica = self._replicas.setdefault(task, Replica(task))
                if kind is not None:
                    replica.kind = kind
            for replica in self._replicas.values():
                self._refresh_one(replica, force)
            healthy = self._healthy_locked()
            self._registry.gauge("fleet/healthy_replicas").set(len(healthy))
            return healthy

    def _discover_tasks(self) -> Dict[str, Optional[str]]:
        """Task -> kind map of advertised replicas. Kind is ``None``
        (unknown, resolved at refresh from whichever endpoint key the
        task published) for an explicit ``tasks=`` list; the KV scan
        path learns it from the matching suffix directly."""
        from tf_yarn_tpu import event

        if self._tasks is not None:
            return {task: None for task in self._tasks}
        suffixes = {
            f"/{event.SERVING_ENDPOINT}": KIND_GENERATE,
            f"/{event.RANK_ENDPOINT}": KIND_RANK,
            f"/{event.PREFILL_ENDPOINT}": KIND_PREFILL,
        }
        try:
            keys = self._kv.keys("")
        except Exception:
            _logger.warning(
                "registry KV key scan failed; keeping known replicas",
                exc_info=True,
            )
            return {task: None for task in self._replicas}
        found: Dict[str, Optional[str]] = {}
        for key in keys:
            for suffix, kind in suffixes.items():
                if key.endswith(suffix):
                    found[key[: -len(suffix)]] = kind
        return dict(sorted(found.items()))

    def _refresh_one(self, replica: Replica, force: bool) -> None:
        from tf_yarn_tpu import event

        try:
            # Read the endpoint from the replica's own kind's key first;
            # when the kind is not yet known (explicit tasks= list),
            # whichever key the task published resolves it — the suffix
            # IS the capability declaration.
            kind_keys = {
                KIND_GENERATE: event.SERVING_ENDPOINT,
                KIND_RANK: event.RANK_ENDPOINT,
                KIND_PREFILL: event.PREFILL_ENDPOINT,
            }
            ordered = [replica.kind] + [
                kind for kind in kind_keys if kind != replica.kind
            ]
            endpoint = None
            for kind in ordered:
                endpoint = self._kv.get_str(
                    f"{replica.task}/{kind_keys[kind]}"
                )
                if endpoint is not None:
                    replica.kind = kind
                    break
            stopped = (
                self._kv.get_str(
                    f"{replica.task}/{event.HEARTBEAT_STOPPED}"
                ) is not None
                or self._kv.get_str(f"{replica.task}/{event.STOP}")
                is not None
            )
            beat_raw = self._kv.get_str(f"{replica.task}/{event.HEARTBEAT}")
        except Exception:
            # A flaky KV read degrades the view for one refresh (the
            # watchdog's posture) — previous states hold.
            _logger.warning(
                "registry KV read for %s failed; keeping previous state",
                replica.task, exc_info=True,
            )
            return
        if endpoint is None:
            return  # not advertised yet: nothing to probe
        if replica.endpoint is not None and endpoint != replica.endpoint:
            # Relaunched incarnation: the task re-advertised the SAME KV
            # key with a NEW host:port. The cached address is dead weight
            # — replace it NOW and clear the probe clock so THIS refresh
            # probes the new address instead of waiting out the throttle
            # (or worse, keeping a HEALTHY replica routed to the corpse).
            _logger.info(
                "replica %s re-advertised %s (was %s); probing the new "
                "address", replica.task, endpoint, replica.endpoint,
            )
            replica.endpoint = endpoint
            replica.last_probe_at = None
            replica.relaunches += 1
            self._registry.counter("fleet/replica_relaunches_total").inc()
            if replica.state in (HEALTHY, STOPPED):
                # Out of rotation until the new incarnation proves
                # healthy; EJECTED stays ejected so the healthy probe
                # below counts a readmission.
                replica.state = PENDING
                replica.eject_reason = None
        else:
            replica.endpoint = endpoint
        if stopped:
            if (replica.stopped_endpoint is None
                    or replica.stopped_endpoint == endpoint):
                # Finished is not dead: out of rotation, no ejection
                # counted.
                replica.state = STOPPED
                replica.stopped_endpoint = endpoint
                return
            # The tombstone predates the current incarnation (the task
            # re-advertised a NEW endpoint after stopping): stale — fall
            # through and probe the live address.
        if beat_raw is not None:
            replica.ever_beat = True
            age = heartbeat_age(beat_raw, now=self._wall_clock())
            if (
                self.dead_heartbeat_s is not None
                and age is not None
                and age > self.dead_heartbeat_s
            ):
                # Beat-then-silent: the scheduler thread is gone even if
                # the socket still answers — do not probe it back in.
                if replica.state == HEALTHY:
                    self._eject(replica, "heartbeat_silent")
                return
        now = self._clock()
        if (
            not force
            and replica.last_probe_at is not None
            and now - replica.last_probe_at < self.probe_interval_s
        ):
            return
        replica.last_probe_at = now
        try:
            payload = self._probe(replica.endpoint)
        except Exception as exc:
            kind = classify_exception(exc)
            _logger.info(
                "probe of %s (%s) failed (%s: %s)", replica.task,
                replica.endpoint, kind.value, exc,
            )
            if replica.state == HEALTHY:
                self._eject(replica, "unreachable")
            # PENDING stays pending: admission held until first health.
            return
        replica.queue_depth = int(payload.get("queue_depth") or 0)
        replica.active_slots = int(payload.get("active_slots") or 0)
        version = payload.get("schema_version")
        try:
            replica.schema_version = (
                int(version) if version is not None else None
            )
        except (TypeError, ValueError):
            replica.schema_version = None
        status = payload.get("status")
        if status != "ok":
            # "draining" lands here: ejected while the replica is still
            # answering — the router stops sending BEFORE the socket dies.
            if replica.state == HEALTHY:
                self._eject(replica, str(status or "unhealthy"))
            return
        if replica.state == EJECTED:
            replica.readmissions += 1
            self._registry.counter("fleet/replica_readmissions_total").inc()
            _logger.info(
                "replica %s recovered (was ejected: %s); re-admitting",
                replica.task, replica.eject_reason,
            )
        replica.state = HEALTHY
        replica.eject_reason = None

    def _eject(self, replica: Replica, reason: str) -> None:
        replica.state = EJECTED
        replica.eject_reason = reason
        replica.ejections += 1
        self._registry.counter(
            "fleet/replica_ejections_total", reason=reason
        ).inc()
        _logger.warning(
            "ejecting replica %s (%s): %s", replica.task, replica.endpoint,
            reason,
        )

    # -- router-observed failures ------------------------------------------

    def report_failure(self, task: str, exc: BaseException) -> None:
        """A forward to `task` failed at the router: eject it NOW (the
        next request must route elsewhere without waiting a probe
        interval) and clear its probe clock so the next refresh probes
        for recovery immediately."""
        kind = classify_exception(exc)
        with self._lock:
            replica = self._replicas.get(task)
            if replica is None:
                return
            if replica.state == HEALTHY:
                self._eject(replica, f"request_{kind.value.lower()}")
            replica.last_probe_at = None
            self._registry.gauge("fleet/healthy_replicas").set(
                len(self._healthy_locked())
            )

    def note_inflight(self, task: str, delta: int) -> None:
        with self._lock:
            replica = self._replicas.get(task)
            if replica is not None:
                replica.inflight = max(0, replica.inflight + delta)

    # -- views --------------------------------------------------------------

    def _healthy_locked(
        self, kind: Optional[str] = None
    ) -> List[Replica]:
        return sorted(
            (
                r for r in self._replicas.values()
                if r.state == HEALTHY
                and (kind is None or r.kind == kind)
            ),
            key=lambda r: r.task,
        )

    def healthy(self, kind: Optional[str] = None) -> List[Replica]:
        """The routable set, optionally restricted to one capability
        kind — the router passes the kind its request path demands, so
        a /v1/rank request can never land on a generate replica.

        Returns per-call COPIES made under the lock: routing policies
        read load fields lock-free on their own threads, and a live
        Replica could be half-mutated by a concurrent refresh probe
        (the lockset scenario suite gates this)."""
        with self._lock:
            return [
                dataclasses.replace(r) for r in self._healthy_locked(kind)
            ]

    def get(self, task: str) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(task)

    def snapshot(self) -> dict:
        with self._lock:
            replicas = {
                task: replica.snapshot()
                for task, replica in sorted(self._replicas.items())
            }
            return {
                "replicas": replicas,
                "healthy_replicas": len(self._healthy_locked()),
                "ejections_total": sum(
                    r.ejections for r in self._replicas.values()
                ),
                "readmissions_total": sum(
                    r.readmissions for r in self._replicas.values()
                ),
            }
