"""Serving fleet: router + replica registry for multi-replica serving.

The scale-out layer over `tf_yarn_tpu/serving/` (docs/Fleet.md): N
independent ``serving`` replicas stay exactly as PR 5–6 built them —
same step programs, same HTTP surface — and this package adds the
framework-owned placement TF-Replicator argues for (PAPERS.md):

* :mod:`~tf_yarn_tpu.fleet.registry` — the live replica set, built from
  the KV ``{task}/serving_endpoint`` advertisements and
  ``{task}/heartbeat`` beats the serving tasks already publish, with
  ``/healthz``-probe health ejection (hold-until-healthy admission,
  draining-aware, re-admission on recovery).
* :mod:`~tf_yarn_tpu.fleet.policy` — balancing policies: round-robin
  and least-loaded (cached ``/healthz`` occupancy + router in-flight).
* :mod:`~tf_yarn_tpu.fleet.monitor` — the fleet observability plane:
  a scrape thread that merges per-replica windowed histogram sketches
  (from each ``/stats`` ``signals`` block) into TRUE pooled fleet
  quantiles, with last-good/stale degradation and fleet-scope SLO
  evaluation — the aggregate signal the autoscaler and canary
  rollback consume.
* :mod:`~tf_yarn_tpu.fleet.autoscaler` — the self-healing elastic
  loop: per-kind `AutoscalePolicy` thresholds over the monitor
  aggregate (queue depth, fleet p95, SLO burn) drive scale-out /
  scale-in decisions through a pluggable actuator, and generate
  replicas (re-)entering the healthy set are warm-started by pulling
  hot prefix-cache blocks from a live peer (``/v1/blocks``).
* :mod:`~tf_yarn_tpu.fleet.router` — the router HTTP task: the same
  ``/v1/generate`` (streaming passthrough) / ``/healthz`` / ``/stats``
  surface as one replica, with budgeted retry-on-another-replica
  failover and 503 + Retry-After when the fleet is empty; `run_router`
  is the ``router`` task-type body (tasks/router.py,
  `topologies.fleet_topology`).
"""

from tf_yarn_tpu.fleet.autoscaler import (  # noqa: F401
    AutoscalePolicy,
    FleetAutoscaler,
    ScaleEvent,
    parse_autoscale,
)
from tf_yarn_tpu.fleet.monitor import (  # noqa: F401
    FleetMonitor,
    http_scrape,
)
from tf_yarn_tpu.fleet.policy import (  # noqa: F401
    POLICIES,
    LeastLoadedPolicy,
    RoundRobinPolicy,
    make_policy,
)
from tf_yarn_tpu.fleet.registry import (  # noqa: F401
    EJECTED,
    HEALTHY,
    PENDING,
    STOPPED,
    Replica,
    ReplicaRegistry,
    http_probe,
)
from tf_yarn_tpu.fleet.router import RouterServer, run_router  # noqa: F401

__all__ = [
    "AutoscalePolicy",
    "EJECTED",
    "FleetAutoscaler",
    "FleetMonitor",
    "HEALTHY",
    "LeastLoadedPolicy",
    "PENDING",
    "POLICIES",
    "Replica",
    "ReplicaRegistry",
    "RoundRobinPolicy",
    "RouterServer",
    "STOPPED",
    "ScaleEvent",
    "http_probe",
    "http_scrape",
    "make_policy",
    "parse_autoscale",
    "run_router",
]
