"""FleetMonitor: the router-side scrape-and-merge aggregator.

The registry answers "which replicas are routable"; the monitor
answers "how is the fleet doing". On each cycle (defaulting to the
registry's probe interval, floored at ``MIN_DEFAULT_INTERVAL_S`` —
a ``/stats`` scrape serializes every replica's sketches, so it must
never inherit a sub-second health-probe cadence) it GETs ``/stats``
from every HEALTHY replica, pulls the
versioned ``signals`` block, and MERGES the per-replica windowed
histogram sketches bucket-for-bucket into fleet aggregates: the fleet
TTFT p95 is a true pooled quantile over every replica's recent
observations, not a max-of-p95s (which has no error bound) or an
average (which is meaningless for quantiles).

Degradation mirrors the registry's KV-flake posture:

* a scrape failure keeps that replica's LAST-GOOD signals in the merge,
  marked ``stale`` (both per replica and as a count on the aggregate);
  a recovered replica re-enters with fresh signals on the next cycle;
* a legacy replica (no ``signals`` block / old ``schema_version``)
  stays routable and is reported ``legacy`` — it simply contributes no
  histograms (mixed-version fleets during a rollout);
* an empty fleet yields an explicit ``{"status": "no_data"}``
  aggregate — never fabricated zeros (a zero fleet p95 would read as
  "infinitely fast", the worst possible lie to an autoscaler).

The merged aggregate is published three ways: `aggregate()` (the
router embeds it in ``/stats`` — the autoscaler input for ROADMAP item
1), ``fleet/<metric>{agg=pNN}`` gauges in the process registry (so the
router's ``/metrics`` exposes fleet quantiles to any Prometheus
scraper), and — when ``slo=`` objectives are declared — a fleet-scoped
`SloEvaluator` pass over the merged histograms feeding
``slo/attainment{scope=fleet}`` / ``slo/burn_total{scope=fleet}``, the
rollback trigger for ROADMAP item 4.

Threading: one joined daemon thread (started by `start()`, joined by
`stop()` — the TYA303 lifecycle contract); every read or write of the
monitor's state goes through ``self._lock``, and `aggregate()` returns
deep copies so handler threads never alias mutating state (the
``fleet.monitor`` lockset scenario gates this).
"""

from __future__ import annotations

import copy
import http.client
import json
import logging
import threading
import time
from typing import Any, Callable, Dict, Optional

from tf_yarn_tpu import telemetry
from tf_yarn_tpu.fleet.registry import (
    DEFAULT_PROBE_TIMEOUT_S,
    ReplicaRegistry,
)
from tf_yarn_tpu.telemetry.registry import Histogram
from tf_yarn_tpu.telemetry.slo import SloEvaluator, parse_slo

_logger = logging.getLogger(__name__)

# Quantiles published per merged histogram, both in the aggregate dict
# and as fleet/<metric>{agg=...} gauges.
_AGGS = (("p50", 0.5), ("p95", 0.95), ("p99", 0.99))

# Floor on the *defaulted* scrape cadence. Health probes are cheap and
# are commonly configured well under a second; a /stats scrape makes
# every replica serialize its full signals block, so piggybacking on a
# sub-second probe interval would turn the monitor into a load
# generator. An explicit ``interval_s=`` is honored verbatim.
MIN_DEFAULT_INTERVAL_S = 1.0


def http_scrape(endpoint: str,
                timeout: float = DEFAULT_PROBE_TIMEOUT_S) -> dict:
    """GET ``/stats`` on a replica; parsed JSON on HTTP 200, raises
    otherwise. The default scrape — tests inject fakes through the
    ``scrape=`` seam exactly like the registry's ``probe=``."""
    host, _, port = endpoint.rpartition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request("GET", "/stats")
        resp = conn.getresponse()
        payload = resp.read()
        if resp.status != 200:
            raise ConnectionError(
                f"/stats on {endpoint} answered {resp.status}"
            )
        return json.loads(payload or b"{}")
    finally:
        conn.close()


class FleetMonitor:
    """Scrape HEALTHY replicas' signals, merge into fleet aggregates."""

    def __init__(
        self,
        registry: ReplicaRegistry,
        *,
        scrape: Callable[[str], dict] = http_scrape,
        interval_s: Optional[float] = None,
        slo: Optional[Dict[str, float]] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._fleet = registry
        self._scrape = scrape
        self.interval_s = float(
            interval_s if interval_s is not None
            else max(registry.probe_interval_s, MIN_DEFAULT_INTERVAL_S)
        )
        self._clock = clock
        self._metrics = telemetry.get_registry()
        self._slo_evaluator: Optional[SloEvaluator] = None
        if slo:
            self._slo_evaluator = SloEvaluator(
                parse_slo(slo), self._metrics, scope="fleet",
            )
        self._lock = threading.Lock()
        # task -> last successfully-scraped signals payload (the
        # last-good fallback a failed scrape falls back to).
        self._last_good: Dict[str, Dict[str, Any]] = {}
        self._aggregate: Dict[str, Any] = {"status": "no_data",
                                           "replicas": {}}
        self._cycles = 0
        self._stop = threading.Event()
        self._lifecycle = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        with self._lifecycle:
            if self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, name="fleet-monitor", daemon=True,
                )
                self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._lifecycle:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10.0)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:
                _logger.warning("fleet monitor cycle failed", exc_info=True)
            self._stop.wait(self.interval_s)

    # -- one scrape-and-merge cycle ------------------------------------

    def poll_once(self) -> Dict[str, Any]:
        """Scrape every healthy replica, rebuild the merged aggregate,
        publish gauges + fleet SLO. Returns the fresh aggregate."""
        replicas = self._fleet.healthy()
        replica_views: Dict[str, Dict[str, Any]] = {}
        merged: Dict[str, Histogram] = {}
        contributing = 0
        stale = 0
        scrape_wall = 0.0
        for replica in replicas:
            if not replica.endpoint:
                continue
            view: Dict[str, Any] = {"kind": replica.kind, "stale": False,
                                    "legacy": False}
            began = self._clock()
            try:
                payload = self._scrape(replica.endpoint)
            except Exception as exc:
                elapsed = self._clock() - began
                scrape_wall += elapsed
                self._metrics.counter(
                    "fleet/monitor_scrapes_total", outcome="error").inc()
                _logger.info("signals scrape of %s (%s) failed: %s",
                             replica.task, replica.endpoint, exc)
                with self._lock:
                    payload = self._last_good.get(replica.task)
                if payload is None:
                    # Never scraped: nothing to fall back to; the
                    # replica stays routable, just unobserved.
                    view["stale"] = True
                    view["signals"] = "never_scraped"
                    replica_views[replica.task] = view
                    stale += 1
                    continue
                view["stale"] = True
                stale += 1
            else:
                elapsed = self._clock() - began
                scrape_wall += elapsed
                self._metrics.counter(
                    "fleet/monitor_scrapes_total", outcome="ok").inc()
                self._metrics.histogram(
                    "fleet/monitor_scrape_seconds").observe(elapsed)
                with self._lock:
                    self._last_good[replica.task] = payload
            view["schema_version"] = payload.get("schema_version")
            signals = payload.get("signals")
            if not isinstance(signals, dict):
                # Pre-observability replica: /stats without a signals
                # block. Keep it routable; it contributes nothing.
                view["legacy"] = True
                replica_views[replica.task] = view
                continue
            contributed = False
            for key, signal in (signals.get("histograms") or {}).items():
                shard = Histogram.from_signal(signal)
                if shard is None:
                    continue  # version/scheme mismatch: skip this one
                contributed = True
                if key in merged:
                    merged[key].merge(shard)
                else:
                    merged[key] = shard
            if contributed or not (signals.get("histograms") or {}):
                contributing += 1
            replica_views[replica.task] = view

        aggregate = self._build_aggregate(
            replica_views, merged, contributing, stale, scrape_wall,
        )
        with self._lock:
            self._cycles += 1
            aggregate["cycle"] = self._cycles
            self._aggregate = aggregate
        self._publish(merged, stale)
        return self.aggregate()

    def _build_aggregate(
        self,
        replica_views: Dict[str, Dict[str, Any]],
        merged: Dict[str, Histogram],
        contributing: int,
        stale: int,
        scrape_wall: float,
    ) -> Dict[str, Any]:
        if not replica_views or not merged:
            # Explicitly NOT zeros: an empty fleet (or one with no
            # signal-bearing replica yet) must not read as "instant".
            return {
                "status": "no_data",
                "replicas": replica_views,
                "stale_replicas": stale,
            }
        histograms: Dict[str, Dict[str, float]] = {}
        for key, hist in sorted(merged.items()):
            summ = hist.summary()
            histograms[key] = summ
        return {
            "status": "ok",
            "replicas": replica_views,
            "contributing_replicas": contributing,
            "stale_replicas": stale,
            "scrape_wall_s": scrape_wall,
            "histograms": histograms,
            "slo": (self._slo_evaluator.evaluate(histograms=merged)
                    if self._slo_evaluator is not None else {}),
        }

    def _publish(self, merged: Dict[str, Histogram], stale: int) -> None:
        self._metrics.gauge("fleet/monitor_stale_replicas").set(stale)
        for key, hist in merged.items():
            if "{" in key:
                # Labeled shards (e.g. per-tier TTFT) stay in the
                # aggregate dict; the gauge namespace publishes the
                # unlabeled headline series.
                continue
            for agg, q in _AGGS:
                est = hist.quantile(q)
                if est is not None:
                    self._metrics.gauge(
                        f"fleet/{key}", agg=agg).set(est)
            self._metrics.gauge(f"fleet/{key}", agg="count").set(hist.count)

    # -- views ---------------------------------------------------------

    def aggregate(self) -> Dict[str, Any]:
        """The latest merged fleet view (deep copy; handler threads may
        call this concurrently with the scrape thread)."""
        with self._lock:
            return copy.deepcopy(self._aggregate)
