"""Fleet autoscaler: FleetMonitor signals in, replica-count actuation out.

ROADMAP item 1's marriage of PR 18's observability plane (the monitor's
merged fleet quantiles + SLO burn) and PR 8's elastic machinery (the
driver's relaunch-with-resize, now per task type): a router side-car
thread watches the fleet aggregate and the registry, and grows/shrinks
the PER-KIND replica count (generate and rank pools independently —
path-aware dispatch means their load is independent too) through a
pluggable actuator:

* in-process harnesses (tests, `benchmarks/run.py fleet --autoscale`)
  pass an ``actuate=`` callable that spawns/drains replicas directly;
* the cluster path records the desired count in the coordination KV
  (``event.fleet_desired_event``) where the driver's elastic relaunch
  path (`client.py` with ``elastic_policy={"serving": ...}``) — and any
  operator — reads it; the decision plane and the relaunch actuator
  compose through the registry's re-admission, not a private RPC.

Decisions are deliberately boring (thresholds + step + cooldown —
an autoscaler you can explain is one you can debug at 3am):

* **scale out** when the kind's fleet is below ``min_replicas``
  (self-healing: ignores cooldown), when mean queue depth per healthy
  replica crosses ``scale_out_queue_depth``, when the kind's latency
  signal (fleet-merged p95 — TTFT for generate, request latency for
  rank) crosses ``scale_out_p95_s``, or when any of the kind's SLO
  objectives reports ``violated`` (the burn signal);
* **scale in** when every live replica is healthy, nothing is queued,
  and mean load sits under ``scale_in_load`` — never below
  ``min_replicas``;
* a ``cooldown_cycles`` refractory period follows every decision so
  relaunch lag (capacity that is coming but not healthy yet counts as
  live) cannot trigger oscillation.

**Peer warm start**: when a generate replica enters the healthy set at
an endpoint the autoscaler has not seen its task at — a relaunched
preemption victim on a new port or a fresh scale-out; a same-endpoint
readmission kept its cache — the autoscaler pulls the hottest
prefix-cache blocks
from a live peer (``GET /v1/blocks``) and pushes them to the newcomer
(``POST /v1/blocks``), relaying the wire bytes verbatim. The blake2b
prefix hashes are content addresses, so the newcomer's first hot-prefix
request hits its cache: TTFT parity with a warm replica instead of a
cold prefill.

Threading: one joined daemon thread (`start()`/`stop()`, the TYA303
lifecycle contract). `poll_once` gathers external views first (registry
snapshot, monitor aggregate — their own locks), plans under
``self._lock``, actuates and warm-starts with NO lock held (HTTP must
never serialize against `stats()`), then records under the lock again.
The ``fleet.autoscaler`` lockset scenario gates this.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from tf_yarn_tpu import telemetry
from tf_yarn_tpu.fleet.monitor import FleetMonitor
from tf_yarn_tpu.fleet.registry import (
    HEALTHY,
    KIND_GENERATE,
    KIND_PREFILL,
    KIND_RANK,
    PENDING,
    ReplicaRegistry,
)

_logger = logging.getLogger(__name__)

KINDS = (KIND_GENERATE, KIND_RANK, KIND_PREFILL)

# Bounds on the launch-ETA hint the router's empty-fleet 503s carry as
# Retry-After: the floor keeps clients from hammering a fleet that is
# seconds from capacity, the ceiling keeps a misconfigured ETA from
# parking clients for an hour on a fleet that heals in one relaunch.
LAUNCH_ETA_FLOOR_S = 1.0
LAUNCH_ETA_CEILING_S = 600.0
DEFAULT_LAUNCH_ETA_S = 15.0

DEFAULT_INTERVAL_S = 1.0

# The fleet-merged latency histogram each kind's p95 trigger reads.
DEFAULT_SIGNALS = {
    KIND_GENERATE: "serving/ttft_seconds",
    KIND_RANK: "ranking/request_seconds",
    # Prefill replicas report their per-request build latency; a
    # saturated tier shows up as a fattening p95 (the tier has no queue
    # of its own — decode replicas fall back locally instead of
    # waiting, so latency IS the pressure signal).
    KIND_PREFILL: "serving/prefill_build_seconds",
}

# SLO objectives are matched to a kind by their metric prefix: a burn
# on serving/* scales the generate pool, ranking/* the rank pool.
# Prefill shares the serving/ namespace but must not double-claim those
# burns — a TTFT burn scales the GENERATE pool (local fallback keeps it
# the bottleneck); the prefill tier scales on its p95 signal alone.
_KIND_METRIC_PREFIXES = {
    KIND_GENERATE: ("serving/",),
    KIND_RANK: ("ranking/",),
    KIND_PREFILL: (),
}


def clamp_launch_eta(eta_s: float) -> float:
    """The bounded launch-ETA the router advertises (floor/ceiling)."""
    return min(LAUNCH_ETA_CEILING_S, max(LAUNCH_ETA_FLOOR_S, float(eta_s)))


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Per-kind scaling policy (module docstring for semantics)."""

    min_replicas: int = 1
    max_replicas: int = 1
    scale_out_queue_depth: Optional[float] = 4.0
    scale_out_p95_s: Optional[float] = None
    scale_in_load: Optional[float] = 0.5
    step: int = 1
    cooldown_cycles: int = 2
    signal: Optional[str] = None  # histogram key; kind default if None

    def __post_init__(self):
        if self.min_replicas < 0:
            raise ValueError(
                f"min_replicas must be >= 0, got {self.min_replicas}"
            )
        if self.max_replicas < max(1, self.min_replicas):
            raise ValueError(
                f"max_replicas ({self.max_replicas}) must be >= "
                f"max(1, min_replicas={self.min_replicas})"
            )
        if self.step < 1:
            raise ValueError(f"step must be >= 1, got {self.step}")
        if self.cooldown_cycles < 0:
            raise ValueError(
                f"cooldown_cycles must be >= 0, got {self.cooldown_cycles}"
            )
        for knob in ("scale_out_queue_depth", "scale_out_p95_s",
                     "scale_in_load"):
            value = getattr(self, knob)
            if value is not None and not float(value) > 0:
                raise ValueError(f"{knob} must be > 0 or None, got {value}")


def parse_autoscale(spec: Dict[str, Any]) -> Dict[str, AutoscalePolicy]:
    """Validate an ``autoscale=`` experiment knob: a dict keyed by
    replica kind (``generate`` / ``rank`` / ``prefill``) whose values are
    `AutoscalePolicy` field dicts (or ready policies). Raises ValueError
    naming the offending key, in the experiment-validation style."""
    if not isinstance(spec, dict) or not spec:
        raise ValueError(
            "autoscale must be a non-empty dict keyed by replica kind "
            f"('generate' / 'rank' / 'prefill'), got {spec!r}"
        )
    policies: Dict[str, AutoscalePolicy] = {}
    for kind, policy in spec.items():
        if kind not in KINDS:
            raise ValueError(
                f"autoscale kind {kind!r} unknown; expected one of {KINDS}"
            )
        if isinstance(policy, AutoscalePolicy):
            policies[kind] = policy
            continue
        if not isinstance(policy, dict):
            raise ValueError(
                f"autoscale[{kind!r}] must be a dict of AutoscalePolicy "
                f"fields, got {policy!r}"
            )
        try:
            policies[kind] = AutoscalePolicy(**policy)
        except TypeError as exc:
            raise ValueError(f"autoscale[{kind!r}]: {exc}") from None
    return policies


@dataclasses.dataclass(frozen=True)
class ScaleEvent:
    """One actuated decision, kept in the history `stats()` serves."""

    kind: str
    direction: str  # "out" | "in"
    from_replicas: int
    to_replicas: int
    reason: str
    cycle: int


def http_fetch_blocks(endpoint: str, timeout: float = 10.0) -> bytes:
    """``GET /v1/blocks`` on a donor replica; raw wire bytes on 200,
    raises otherwise. The warm-start pull — injectable seam."""
    host, _, port = endpoint.rpartition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request("GET", "/v1/blocks")
        resp = conn.getresponse()
        payload = resp.read()
        if resp.status != 200:
            raise ConnectionError(
                f"/v1/blocks on {endpoint} answered {resp.status}"
            )
        return payload
    finally:
        conn.close()


def http_push_blocks(endpoint: str, body: bytes,
                     timeout: float = 10.0) -> dict:
    """``POST /v1/blocks`` to a newcomer replica; parsed JSON on 200,
    raises otherwise. The warm-start push — injectable seam."""
    host, _, port = endpoint.rpartition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request("POST", "/v1/blocks", body=body, headers={
            "Content-Type": "application/json",
            "Content-Length": str(len(body)),
        })
        resp = conn.getresponse()
        payload = resp.read()
        if resp.status != 200:
            raise ConnectionError(
                f"/v1/blocks push to {endpoint} answered {resp.status}"
            )
        return json.loads(payload or b"{}")
    finally:
        conn.close()


class FleetAutoscaler:
    """Watch the fleet, move the per-kind replica counts (module
    docstring). ``actuate(kind, current, target, reason) -> bool`` is
    the resize actuator; a falsy/raising actuator records nothing and
    the decision is retried after the cooldown. ``actuate=None`` runs
    decision-only (the history and counters are the output — the KV
    advertisement path run_router wires up)."""

    def __init__(
        self,
        registry: ReplicaRegistry,
        monitor: Optional[FleetMonitor],
        policies: Dict[str, AutoscalePolicy],
        *,
        actuate: Optional[Callable[[str, int, int, str], bool]] = None,
        launch_eta_s: float = DEFAULT_LAUNCH_ETA_S,
        interval_s: float = DEFAULT_INTERVAL_S,
        warm_start: bool = True,
        fetch_blocks: Callable[[str], bytes] = http_fetch_blocks,
        push_blocks: Callable[[str, bytes], dict] = http_push_blocks,
        history_limit: int = 64,
    ) -> None:
        if not float(launch_eta_s) > 0:
            raise ValueError(
                f"launch_eta_s must be > 0, got {launch_eta_s}"
            )
        if not float(interval_s) > 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self._fleet = registry
        self._monitor = monitor
        self.policies = dict(parse_autoscale(policies))
        self.launch_eta_s = clamp_launch_eta(launch_eta_s)
        self.interval_s = float(interval_s)
        self.warm_start = bool(warm_start)
        self._actuate = actuate
        self._fetch_blocks = fetch_blocks
        self._push_blocks = push_blocks
        self._history_limit = int(history_limit)
        self._metrics = telemetry.get_registry()
        # Pre-register the decision counters so /stats signals carry
        # explicit zeros before the first event (satellite: asserted).
        for kind in self.policies:
            for direction in ("out", "in"):
                self._metrics.counter(
                    "fleet/scale_events_total",
                    kind=kind, direction=direction,
                )
        self._metrics.counter("fleet/warm_start_blocks_total")
        self._lock = threading.Lock()
        self._cycles = 0
        self._cooldown: Dict[str, int] = {kind: 0 for kind in self.policies}
        self._history: List[ScaleEvent] = []
        self._warm_starts: List[Dict[str, Any]] = []
        # Warm-start bookkeeping: the endpoint each task was last seen
        # healthy at. A healthy task at a NEW endpoint is a fresh
        # process (relaunch/scale-out) with a cold cache; a readmission
        # at the SAME endpoint kept its cache and needs nothing.
        self._known_endpoints: Dict[str, str] = {}
        self._stop = threading.Event()
        self._lifecycle = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        with self._lifecycle:
            if self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, name="fleet-autoscaler", daemon=True,
                )
                self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._lifecycle:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10.0)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:
                _logger.warning("autoscaler cycle failed", exc_info=True)
            self._stop.wait(self.interval_s)

    # -- one decision cycle --------------------------------------------

    def poll_once(self) -> Dict[str, Any]:
        """Gather → plan → actuate → record. Returns a cycle report
        (decisions planned, actuated, warm starts performed)."""
        aggregate = (self._monitor.aggregate()
                     if self._monitor is not None else {})
        snapshot = self._fleet.snapshot()
        with self._lock:
            decisions = self._plan_locked(aggregate, snapshot)
            warm_candidates = self._warm_candidates_locked(snapshot)
        actuated: List[ScaleEvent] = []
        for event in decisions:
            ok = True
            if self._actuate is not None:
                try:
                    ok = bool(self._actuate(
                        event.kind, event.from_replicas,
                        event.to_replicas, event.reason,
                    ))
                except Exception:
                    _logger.warning(
                        "autoscaler actuation failed: %s", event,
                        exc_info=True,
                    )
                    ok = False
            if ok:
                self._metrics.counter(
                    "fleet/scale_events_total",
                    kind=event.kind, direction=event.direction,
                ).inc()
                _logger.info("fleet scale %s: %s", event.direction, event)
                actuated.append(event)
        warm_results = [
            self._warm_start_one(task, endpoint, donor)
            for task, endpoint, donor in warm_candidates
        ]
        with self._lock:
            self._cycles += 1
            self._history.extend(actuated)
            del self._history[:-self._history_limit]
            self._warm_starts.extend(warm_results)
            del self._warm_starts[:-self._history_limit]
            cycle = self._cycles
        return {
            "cycle": cycle,
            "decisions": [dataclasses.asdict(e) for e in decisions],
            "actuated": [dataclasses.asdict(e) for e in actuated],
            "warm_starts": warm_results,
        }

    def _plan_locked(self, aggregate: Dict[str, Any],
                     snapshot: Dict[str, Any]) -> List[ScaleEvent]:
        histograms = aggregate.get("histograms") or {}
        slo = aggregate.get("slo") or {}
        decisions: List[ScaleEvent] = []
        replicas = list((snapshot.get("replicas") or {}).values())
        for kind, policy in self.policies.items():
            pool = [r for r in replicas if r.get("kind") == kind]
            live = [r for r in pool if r.get("state") in (PENDING, HEALTHY)]
            healthy = [r for r in pool if r.get("state") == HEALTHY]
            current = len(live)
            # Self-healing floor: ignores cooldown — a fleet below its
            # minimum must not wait out a refractory period.
            if current < policy.min_replicas:
                decisions.append(self._decide_locked(
                    kind, policy, current,
                    min(policy.max_replicas,
                        max(policy.min_replicas, current + policy.step)),
                    "below_min",
                ))
                continue
            if self._cooldown.get(kind, 0) > 0:
                self._cooldown[kind] -= 1
                continue
            reason = self._scale_out_reason_locked(
                kind, policy, healthy, histograms, slo,
            )
            if reason is not None and current < policy.max_replicas:
                decisions.append(self._decide_locked(
                    kind, policy, current,
                    min(policy.max_replicas, current + policy.step),
                    reason,
                ))
                continue
            if (
                policy.scale_in_load is not None
                and current > policy.min_replicas
                and healthy and len(healthy) == current
            ):
                load = sum(
                    (r.get("queue_depth") or 0)
                    + (r.get("active_slots") or 0)
                    + (r.get("inflight") or 0)
                    for r in healthy
                ) / len(healthy)
                if load < policy.scale_in_load:
                    decisions.append(self._decide_locked(
                        kind, policy, current,
                        max(policy.min_replicas, current - policy.step),
                        f"idle_load_{load:.2f}",
                    ))
        return decisions

    def _decide_locked(self, kind: str, policy: AutoscalePolicy,
                       current: int, target: int, reason: str) -> ScaleEvent:
        self._cooldown[kind] = policy.cooldown_cycles
        return ScaleEvent(
            kind=kind,
            direction="out" if target > current else "in",
            from_replicas=current,
            to_replicas=target,
            reason=reason,
            cycle=self._cycles + 1,
        )

    def _scale_out_reason_locked(
        self,
        kind: str,
        policy: AutoscalePolicy,
        healthy: List[Dict[str, Any]],
        histograms: Dict[str, Any],
        slo: Dict[str, Any],
    ) -> Optional[str]:
        if policy.scale_out_queue_depth is not None and healthy:
            depth = sum(
                (r.get("queue_depth") or 0) for r in healthy
            ) / len(healthy)
            if depth >= policy.scale_out_queue_depth:
                return f"queue_depth_{depth:.2f}"
        signal = policy.signal or DEFAULT_SIGNALS.get(kind)
        if policy.scale_out_p95_s is not None and signal:
            summary = histograms.get(signal) or {}
            p95 = summary.get("p95")
            if p95 is not None and p95 > policy.scale_out_p95_s:
                return f"p95_{p95:.3f}s"
        prefixes = _KIND_METRIC_PREFIXES.get(kind, ())
        for name, entry in sorted(slo.items()):
            metric = str(entry.get("metric") or "")
            if entry.get("status") == "violated" and \
                    metric.startswith(prefixes):
                return f"slo_burn_{name}"
        return None

    # -- peer warm start -----------------------------------------------

    def _warm_candidates_locked(
        self, snapshot: Dict[str, Any]
    ) -> List[Tuple[str, str, str]]:
        """(task, endpoint, donor endpoint) for every generate replica
        that just entered the healthy set AT A NEW ENDPOINT with a warm
        peer available. Endpoint change is the cold-cache signal: a
        scale-out newcomer and a relaunched preemption victim both show
        up at an address this autoscaler has never seen the task at,
        while a same-endpoint readmission (transient probe failure, the
        process never died) kept its cache and is skipped. Bookkeeping
        updates here (optimistically — a failed pull is recorded, not
        retried every cycle)."""
        if not self.warm_start:
            return []
        replicas = (snapshot.get("replicas") or {}).values()
        healthy_gen = [
            r for r in replicas
            if r.get("kind") == KIND_GENERATE
            and r.get("state") == HEALTHY and r.get("endpoint")
        ]
        # First sight of a running fleet: everyone present is warm
        # already (or there is nobody to pull from) — record, no pulls.
        first_sight = not self._known_endpoints
        fresh: List[Dict[str, Any]] = []
        veterans: List[Dict[str, Any]] = []
        for replica in healthy_gen:
            task = replica["task"]
            endpoint = replica["endpoint"]
            previous = self._known_endpoints.get(task)
            self._known_endpoints[task] = endpoint
            if first_sight or previous == endpoint:
                veterans.append(replica)
            else:
                fresh.append(replica)
        # Donors come from the veterans only: a fellow fresh replica is
        # exactly as cold as the puller and a pull from it ships air.
        candidates: List[Tuple[str, str, str]] = []
        for replica in fresh:
            donors = [
                v for v in veterans
                if v["endpoint"] != replica["endpoint"]
            ]
            if not donors:
                continue  # nothing warm to pull from: stays cold
            candidates.append(
                (replica["task"], replica["endpoint"],
                 donors[0]["endpoint"])
            )
        return candidates

    def _warm_start_one(self, task: str, endpoint: str,
                        donor: str) -> Dict[str, Any]:
        record: Dict[str, Any] = {"task": task, "donor": donor}
        try:
            wire = self._fetch_blocks(donor)
            result = self._push_blocks(endpoint, wire)
        except Exception as exc:
            _logger.warning(
                "warm start of %s from %s failed: %s", task, donor, exc,
            )
            record["error"] = str(exc)
            return record
        imported = int(result.get("imported_blocks") or 0)
        record["imported_blocks"] = imported
        record["registered_entries"] = int(
            result.get("registered_entries") or 0
        )
        if imported:
            self._metrics.counter(
                "fleet/warm_start_blocks_total").inc(imported)
        _logger.info(
            "warm-started %s from %s: %d blocks, %d entries",
            task, donor, imported, record["registered_entries"],
        )
        return record

    # -- views ---------------------------------------------------------

    def launch_eta_hint(self) -> float:
        """Seconds until scaled-out capacity should be admitting — the
        Retry-After the router's empty-fleet 503s carry. Already
        clamped to [LAUNCH_ETA_FLOOR_S, LAUNCH_ETA_CEILING_S]."""
        return self.launch_eta_s

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "cycles": self._cycles,
                "launch_eta_s": self.launch_eta_s,
                "policies": {
                    kind: dataclasses.asdict(policy)
                    for kind, policy in sorted(self.policies.items())
                },
                "cooldowns": dict(self._cooldown),
                "scale_events": [
                    dataclasses.asdict(e) for e in self._history
                ],
                "warm_starts": [dict(w) for w in self._warm_starts],
            }
