"""Router HTTP task: one endpoint in front of N serving replicas.

The TF-Replicator argument applied to serving (PAPERS.md): the user
keeps the single-machine-shaped API — the router exposes the IDENTICAL
``/v1/generate`` / ``/healthz`` / ``/stats`` surface as one replica
(serving/server.py) — while the framework owns replica discovery,
placement, and failover behind it. The per-replica serving stack is
untouched; only the replica axis scales.

Mixed fleets: the router also fronts ranking replicas
(ranking/server.py) on the same port — dispatch is PATH-AWARE. The
request path names the capability (``/v1/generate`` -> generate
replicas, ``/v1/rank`` -> rank replicas, as declared by the KV suffix
each replica advertised under), and the policy only ever picks from
``registry.healthy(kind=...)`` — a rank request cannot land on a
generate replica or vice versa, even when both kinds share the fleet.

Same stdlib threaded-server shape as the replica frontend. Per request:

1. pick a healthy replica OF THE REQUEST PATH'S KIND via the
   configured policy (round-robin or least-loaded over cached
   ``/healthz`` occupancy);
2. forward. Connect errors and 429s fail over to ANOTHER replica,
   budgeted through :class:`~tf_yarn_tpu.resilience.retry.RetryPolicy`
   (per-kind budgets + decorrelated jitter; an upstream ``Retry-After``
   is honored as the backoff floor when every replica has been tried);
   a replica observed failing is ejected immediately
   (``registry.report_failure``) so the next request routes elsewhere;
3. streaming passthrough: upstream token lines are re-chunked to the
   client as they arrive, so TTFT through the router is the replica's
   plus one hop. A replica dying MID-stream cannot be retried (the 200
   is on the wire) — the stream ends with a classified error line
   (``{"error": ..., "failure_kind": ...}``) instead;
4. no healthy replica (or budget exhausted): 503 with a ``Retry-After``
   header — shed, don't buffer, the same backpressure posture as the
   replica's 429.

Deterministic 4xx from a replica (400 bad request, 404, 413) passes
through verbatim — retrying a user error elsewhere just reproduces it,
the FATAL_USER posture of the failure taxonomy.

`run_router` is the ``router`` task body (tasks/router.py): build the
registry over the cluster's serving and rank tasks, refresh it on a
poll loop, advertise ``{task}/router_endpoint``, serve until
preemption/duration.
"""

from __future__ import annotations

import http.client
import json
import logging
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from tf_yarn_tpu import telemetry
from tf_yarn_tpu.fleet.policy import make_policy
from tf_yarn_tpu.fleet.registry import (
    KIND_GENERATE,
    KIND_RANK,
    Replica,
    ReplicaRegistry,
)
from tf_yarn_tpu.resilience.retry import RetryPolicy
from tf_yarn_tpu.resilience.taxonomy import FailureKind, classify_exception

_logger = logging.getLogger(__name__)

# Cap on any single failover backoff sleep: a router request handler
# must never hold its connection hostage to a long jitter tail.
MAX_FAILOVER_SLEEP_S = 5.0

# How long the router poll loop sleeps between registry refreshes; the
# refresh itself rate-limits per-replica probes by probe_interval_s.
POLL_S = 0.2

# Request path -> replica capability kind. The path IS the dispatch
# key: anything else 404s, and the policy only sees replicas whose
# advertised kind matches.
PATH_KINDS = {
    "/v1/generate": KIND_GENERATE,
    "/v1/rank": KIND_RANK,
}


class _UpstreamUnreachable(Exception):
    """Connect/read failure BEFORE any byte reached the client: safe to
    fail over to another replica."""

    def __init__(self, replica: Replica, cause: BaseException):
        super().__init__(f"replica {replica.task} unreachable: {cause}")
        self.replica = replica
        self.cause = cause


class _UpstreamBusy(Exception):
    """Upstream 429: that replica's admission queue is full; try
    another, carrying the Retry-After hint."""

    def __init__(self, replica: Replica, retry_after_s: float):
        super().__init__(f"replica {replica.task} busy")
        self.replica = replica
        self.retry_after_s = retry_after_s


class RouterServer:
    """The fleet frontend over one ReplicaRegistry (module docstring)."""

    def __init__(
        self,
        registry: ReplicaRegistry,
        policy=None,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        retries: int = 2,
        retry_after_s: float = 1.0,
        upstream_timeout_s: float = 600.0,
        monitor=None,
        autoscaler=None,
    ):
        self.registry = registry
        self.policy = policy if policy is not None else make_policy(
            "least_loaded"
        )
        self.retries = int(retries)
        self.retry_after_s = float(retry_after_s)
        self.upstream_timeout_s = float(upstream_timeout_s)
        # Optional fleet.FleetMonitor: when attached, its merged
        # aggregate rides /stats (the autoscaler input) and its
        # fleet/* gauges ride /metrics. Lifecycle belongs to the
        # caller (run_router starts/stops it around the serve loop).
        self.monitor = monitor
        # Optional fleet.FleetAutoscaler side-car: when attached, its
        # decision history rides /stats and an EMPTY pool's 503 carries
        # the (clamped) launch ETA as Retry-After — scale-from-zero
        # clients back off for as long as capacity actually takes to
        # arrive, not a fixed second. Lifecycle belongs to the caller.
        self.autoscaler = autoscaler
        self._metrics = telemetry.get_registry()
        self._routed: Dict[str, Dict[str, int]] = {}
        self._routed_lock = threading.Lock()
        self._seq_lock = threading.Lock()
        self._seq = 0
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._lifecycle = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def endpoint(self) -> str:
        host = self._httpd.server_address[0]
        return f"{host}:{self.port}"

    def start(self) -> str:
        with self._lifecycle:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._httpd.serve_forever, name="router-http",
                    daemon=True,
                )
                self._thread.start()
        _logger.info("router frontend listening on %s", self.endpoint)
        return self.endpoint

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        # Snapshot-under-lock: concurrent stop() calls each either own
        # the thread (and join it) or see None; join outside the lock.
        with self._lifecycle:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10.0)

    # -- accounting ---------------------------------------------------------

    def _count(self, replica_task: str, outcome: str) -> None:
        with self._routed_lock:
            per = self._routed.setdefault(replica_task, {})
            per[outcome] = per.get(outcome, 0) + 1
        self._metrics.counter(
            "fleet/routed_requests_total",
            replica=replica_task, outcome=outcome,
        ).inc()

    def _next_seq(self) -> int:
        with self._seq_lock:
            self._seq += 1
            return self._seq

    def stats(self) -> dict:
        """Router snapshot for /stats and the task's flushed metrics."""
        with self._routed_lock:
            routed = {
                task: dict(outcomes)
                for task, outcomes in sorted(self._routed.items())
            }
        out = {
            "schema_version": telemetry.STATS_SCHEMA_VERSION,
            "role": "router",
            "policy": self.policy.name,
            "retries": self.retries,
            "routed_requests": routed,
            **self.registry.snapshot(),
            "signals": telemetry.signals_block(
                prefixes=("fleet/", "slo/", "telemetry/"),
            ),
        }
        if self.monitor is not None:
            out["fleet"] = self.monitor.aggregate()
        if self.autoscaler is not None:
            out["autoscaler"] = self.autoscaler.stats()
        return out


def _make_handler(router: RouterServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            _logger.debug("router http %s", fmt % args)

        # -- helpers (same wire shapes as serving/server.py) -------------

        def _json(self, status: int, payload: dict, headers=()) -> None:
            body = (json.dumps(payload) + "\n").encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for key, value in headers:
                self.send_header(key, value)
            self.end_headers()
            self.wfile.write(body)

        def _raw(self, status: int, body: bytes,
                 content_type: str = "application/json",
                 headers=()) -> None:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for key, value in headers:
                self.send_header(key, value)
            self.end_headers()
            self.wfile.write(body)

        def _chunk_raw(self, data: bytes) -> None:
            self.wfile.write(b"%x\r\n%s\r\n" % (len(data), data))
            self.wfile.flush()

        def _end_chunks(self) -> None:
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()

        # -- routes ------------------------------------------------------

        def do_GET(self):
            if self.path == "/healthz":
                from tf_yarn_tpu import preemption

                healthy = router.registry.healthy()
                draining = preemption.requested()
                by_kind: Dict[str, int] = {}
                for replica in healthy:
                    by_kind[replica.kind] = by_kind.get(
                        replica.kind, 0
                    ) + 1
                self._json(200, {
                    "schema_version": telemetry.STATS_SCHEMA_VERSION,
                    "status": "draining" if draining else "ok",
                    "role": "router",
                    "healthy_replicas": len(healthy),
                    "healthy_by_kind": by_kind,
                })
            elif self.path == "/stats":
                self._json(200, router.stats())
            elif self.path == "/metrics":
                body = telemetry.render_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 telemetry.PROMETHEUS_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._json(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            kind = PATH_KINDS.get(self.path)
            if kind is None:
                self._json(404, {"error": f"unknown path {self.path}"})
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                raw_body = self.rfile.read(length) or b"{}"
                body = json.loads(raw_body)
            except (TypeError, ValueError) as exc:
                self._json(400, {"error": f"bad request: {exc}"})
                return
            stream = bool(body.get("stream"))
            # Cross-task request id: honor a caller-supplied
            # X-Request-Id, mint one otherwise; forwarded to the
            # replica so both sides' spans (and the replica's
            # scheduler trace ring) carry the same id.
            trace_id = (self.headers.get("X-Request-Id")
                        or f"req-{uuid.uuid4().hex[:16]}")
            began = time.monotonic()
            outcome = "client_dropped"
            try:
                with telemetry.span("router/route", request_id=trace_id,
                                    path=self.path):
                    outcome = self._route(
                        raw_body, stream, self.path, kind, trace_id
                    )
            except (BrokenPipeError, ConnectionResetError):
                _logger.info("client dropped routed request")
            finally:
                # Satellite of the observability plane: the router
                # times what it routes (it used to only count).
                router._metrics.histogram(
                    "fleet/routed_request_seconds",
                    path=self.path, outcome=outcome,
                ).observe(time.monotonic() - began)

        # -- the routing loop --------------------------------------------

        def _route(self, raw_body: bytes, stream: bool,
                   path: str, kind: str, trace_id: str) -> str:
            # Per-request failover budget: connect errors and 429s each
            # consume from their kind's budget; deterministic jitter per
            # request sequence number.
            retry_policy = RetryPolicy.from_nb_retries(
                router.retries, seed=router._next_seq()
            )
            tried: set = set()
            busy_hint = 0.0
            last_error = "no healthy replica"
            while True:
                replica = router.policy.pick(
                    router.registry.healthy(kind=kind), exclude=tried
                )
                if replica is None:
                    if not tried:
                        # Maybe the view is just stale (all ejected, or
                        # never refreshed): one forced pass before 503.
                        router.registry.refresh(force=True)
                        if router.registry.healthy(kind=kind):
                            continue
                        self._no_replica(busy_hint, last_error, kind)
                        return "no_replica"
                    # Every healthy replica tried this pass: another
                    # round costs one TRANSIENT retry, backing off with
                    # jitter but never below the upstream Retry-After.
                    delay = retry_policy.next_delay(FailureKind.TRANSIENT)
                    if delay is None:
                        self._no_replica(busy_hint, last_error, kind)
                        return "no_replica"
                    time.sleep(
                        min(max(delay, busy_hint), MAX_FAILOVER_SLEEP_S)
                    )
                    tried.clear()
                    router.registry.refresh(force=True)
                    continue
                try:
                    outcome = self._forward(
                        replica, raw_body, stream, path, trace_id
                    )
                except _UpstreamUnreachable as exc:
                    router._count(replica.task, "connect_error")
                    router.registry.report_failure(replica.task, exc.cause)
                    tried.add(replica.task)
                    last_error = str(exc)
                    failure_kind = classify_exception(exc.cause)
                    if retry_policy.next_delay(failure_kind) is None:
                        self._no_replica(busy_hint, last_error, kind)
                        return "no_replica"
                    continue  # fail over immediately: different replica
                except _UpstreamBusy as exc:
                    router._count(replica.task, "busy")
                    tried.add(replica.task)
                    busy_hint = max(busy_hint, exc.retry_after_s)
                    last_error = (
                        f"replica {replica.task} backpressured (429)"
                    )
                    if retry_policy.next_delay(
                        FailureKind.TRANSIENT
                    ) is None:
                        self._no_replica(busy_hint, last_error, kind)
                        return "no_replica"
                    continue
                _logger.debug("routed request: %s", outcome)
                return outcome

        def _no_replica(self, busy_hint: float, last_error: str,
                        kind: str) -> None:
            # Counted BEFORE the response bytes go out: /stats read right
            # after a reply must already include it.
            router._count("-", "no_replica")
            retry_after = max(router.retry_after_s, busy_hint)
            body = {"retry_after_s": retry_after}
            if (
                router.autoscaler is not None
                and not router.registry.healthy(kind=kind)
            ):
                # Scale-from-zero: the kind's pool is EMPTY (not just
                # busy), so the honest Retry-After is the autoscaler's
                # launch ETA — how long a scaled-out replica takes to
                # become routable — not the fixed shed hint.
                eta = router.autoscaler.launch_eta_hint()
                retry_after = max(retry_after, eta)
                body["scale_out_eta_s"] = eta
            body["retry_after_s"] = retry_after
            body["error"] = (
                f"no {kind} replica available: "
                f"{last_error}; retry in ~{retry_after:.1f}s"
            )
            self._json(
                503,
                body,
                headers=(("Retry-After",
                          str(max(1, int(retry_after)))),),
            )

        def _forward(self, replica: Replica, raw_body: bytes,
                     stream: bool, path: str, trace_id: str) -> str:
            host, _, port = (replica.endpoint or "").rpartition(":")
            conn = http.client.HTTPConnection(
                host, int(port), timeout=router.upstream_timeout_s
            )
            router.registry.note_inflight(replica.task, +1)
            try:
                try:
                    conn.request(
                        "POST", path, raw_body,
                        {"Content-Type": "application/json",
                         "X-Request-Id": trace_id},
                    )
                    resp = conn.getresponse()
                except (OSError, http.client.HTTPException) as exc:
                    raise _UpstreamUnreachable(replica, exc) from exc
                if resp.status == 429:
                    try:
                        retry_after = float(
                            resp.getheader("Retry-After") or 1.0
                        )
                    except ValueError:
                        retry_after = 1.0
                    resp.read()
                    raise _UpstreamBusy(replica, retry_after)
                if not stream or resp.status != 200:
                    try:
                        payload = resp.read()
                    except (OSError, http.client.HTTPException) as exc:
                        # Died mid-body but nothing reached the client
                        # yet: still safe to fail over.
                        raise _UpstreamUnreachable(replica, exc) from exc
                    outcome = (
                        "ok" if resp.status == 200
                        else f"upstream_{resp.status}"
                    )
                    router._count(replica.task, outcome)
                    self._raw(
                        resp.status, payload,
                        resp.getheader("Content-Type")
                        or "application/json",
                        headers=(("X-Request-Id", trace_id),),
                    )
                    return outcome
                return self._forward_stream(replica, resp)
            finally:
                router.registry.note_inflight(replica.task, -1)
                conn.close()

        def _forward_stream(self, replica: Replica, resp) -> str:
            """Chunked passthrough: each upstream token line re-chunks
            to the client as it arrives (TTFT is the replica's plus one
            hop). Mid-stream upstream death cannot fail over — the 200
            is already on the wire — so the stream closes with a
            classified error line and the replica is ejected."""
            self.send_response(resp.status)
            self.send_header(
                "Content-Type",
                resp.getheader("Content-Type") or "application/jsonl",
            )
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            try:
                saw_done = False
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    self._chunk_raw(line)
                    try:
                        saw_done = bool(json.loads(line).get("done"))
                    except ValueError:
                        saw_done = False
                if not saw_done:
                    # Premature EOF reads as a CLEAN end through
                    # http.client (readline's peek swallows
                    # IncompleteRead) — the protocol's closing
                    # {"done": true} line is the real termination
                    # signal, so its absence IS the mid-stream death.
                    raise ConnectionResetError(
                        "stream ended without its done line"
                    )
                router._count(replica.task, "ok")
                self._end_chunks()
                return "ok"
            except (OSError, http.client.HTTPException) as exc:
                kind = classify_exception(exc)
                _logger.warning(
                    "replica %s failed mid-stream (%s): %s",
                    replica.task, kind.value, exc,
                )
                router.registry.report_failure(replica.task, exc)
                router._count(replica.task, "stream_error")
                self._chunk_raw((json.dumps({
                    "error": (
                        f"replica {replica.task} failed mid-stream: {exc}"
                    ),
                    "failure_kind": kind.value,
                    "done": True,
                    "finish_reason": "error",
                }) + "\n").encode())
                self._end_chunks()
                return "stream_error"

    return Handler


def run_router(experiment, runtime) -> dict:
    """Task body for the ``router`` task type: registry over the
    cluster's serving AND rank tasks → policy → frontend → advertise →
    refresh loop. Returns the final router stats snapshot."""
    from tf_yarn_tpu import event, preemption
    from tf_yarn_tpu.resilience.watchdog import dead_task_secs_from_env
    from tf_yarn_tpu.serving.server import advertised_endpoint

    telemetry_task = getattr(
        runtime, "task",
        f"{runtime.task_key.type}:{runtime.task_key.id}",
    )
    telemetry.enable_env_jsonl(telemetry_task)
    serving_tasks = [
        instance.key.to_kv_str()
        for instance in getattr(runtime, "cluster_tasks", [])
        # prefill replicas never receive routed requests (PATH_KINDS is
        # the dispatch key and /v1/generate pulls from the tier), but
        # the registry tracks their health so the monitor merges their
        # signals and the autoscaler can size the tier.
        if instance.key.type in ("serving", "rank", "prefill")
    ] or None  # None -> discover by KV scan
    registry = ReplicaRegistry(
        runtime.kv,
        tasks=serving_tasks,
        probe_interval_s=experiment.router_probe_interval_s,
        dead_heartbeat_s=dead_task_secs_from_env(),
    )
    from tf_yarn_tpu.fleet.monitor import FleetMonitor

    monitor = FleetMonitor(
        registry, slo=getattr(experiment, "slo", None),
    )
    autoscaler = None
    autoscale_spec = getattr(experiment, "autoscale", None)
    if autoscale_spec:
        from tf_yarn_tpu.fleet.autoscaler import FleetAutoscaler

        def _advertise_desired(kind: str, current: int, target: int,
                               reason: str) -> bool:
            # The cluster actuator: publish the desired per-kind count
            # in the coordination KV. The driver's elastic relaunch
            # path (client.py, elastic_policy={'serving': ...}) — and
            # any operator — consumes it; the decision plane and the
            # actuator compose through re-admission, not a private RPC.
            event.fleet_desired_event(
                runtime.kv, runtime.task, kind, target, reason,
            )
            return True

        autoscaler = FleetAutoscaler(
            registry,
            monitor,
            autoscale_spec,
            actuate=_advertise_desired,
            launch_eta_s=getattr(
                experiment, "autoscale_launch_eta_s", None,
            ) or 15.0,
            warm_start=getattr(experiment, "autoscale_warm_start", True),
        )
    server = RouterServer(
        registry,
        make_policy(experiment.router_policy),
        experiment.router_host,
        experiment.router_port,
        retries=experiment.router_retries,
        retry_after_s=experiment.retry_after_s,
        monitor=monitor,
        autoscaler=autoscaler,
    )
    monitor.start()
    if autoscaler is not None:
        autoscaler.start()
    endpoint = server.start()
    advertised = advertised_endpoint(experiment.router_host, server.port)
    event.router_endpoint_event(runtime.kv, runtime.task, advertised)
    _logger.info(
        "router on %s (advertised %s): policy=%s over %s",
        endpoint, advertised, experiment.router_policy,
        serving_tasks or "KV-discovered replicas",
    )
    deadline = (
        time.monotonic() + experiment.serve_seconds
        if experiment.serve_seconds is not None else None
    )
    try:
        while True:
            if preemption.requested():
                _logger.info("router draining on preemption notice")
                break
            if deadline is not None and time.monotonic() >= deadline:
                _logger.info(
                    "serve_seconds=%.1f elapsed; router shutting down",
                    experiment.serve_seconds,
                )
                break
            registry.refresh()
            time.sleep(POLL_S)
    finally:
        if autoscaler is not None:
            autoscaler.stop()
        monitor.stop()
        server.stop()
        stats = {"endpoint": advertised, **server.stats()}
        _logger.info("router done: %s", stats)
        telemetry.flush_metrics(
            telemetry.get_registry(),
            kv=getattr(runtime, "kv", None),
            task=telemetry_task,
        )
        telemetry.export_trace(telemetry_task)
    return stats
