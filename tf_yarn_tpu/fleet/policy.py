"""Balancing policies: which healthy replica gets the next request.

Pure host-side selection over the registry's healthy set — policies
never probe, never block, and take an ``exclude`` set so the router's
retry loop can fail over without re-picking a replica it just watched
fail. Both policies are deterministic given the same replica states,
which is what the fake-registry unit tests pin.
"""

from __future__ import annotations

import threading
from typing import Iterable, Optional, Sequence

from tf_yarn_tpu.fleet.registry import Replica


class RoundRobinPolicy:
    """Cycle the healthy set in task order. Fair regardless of load
    signals — the right default when replicas are homogeneous and the
    /stats poll cadence is slow next to the request rate."""

    name = "round_robin"

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cursor = 0

    def pick(self, replicas: Sequence[Replica],
             exclude: Iterable[str] = ()) -> Optional[Replica]:
        excluded = set(exclude)
        candidates = sorted(
            (r for r in replicas if r.task not in excluded),
            key=lambda r: r.task,
        )
        if not candidates:
            return None
        with self._lock:
            cursor = self._cursor
            self._cursor += 1
        return candidates[cursor % len(candidates)]


class LeastLoadedPolicy:
    """Pick the replica with the smallest load signal: the cached
    ``/healthz`` occupancy (queue depth + active slots) plus the
    router's own in-flight count for that replica — the correction that
    keeps a burst between polls from dogpiling one replica. Ties break
    by task order (deterministic)."""

    name = "least_loaded"

    def pick(self, replicas: Sequence[Replica],
             exclude: Iterable[str] = ()) -> Optional[Replica]:
        excluded = set(exclude)
        candidates = sorted(
            (r for r in replicas if r.task not in excluded),
            key=lambda r: (r.load, r.task),
        )
        return candidates[0] if candidates else None


POLICIES = {
    RoundRobinPolicy.name: RoundRobinPolicy,
    LeastLoadedPolicy.name: LeastLoadedPolicy,
}


def make_policy(name: str):
    """A fresh policy instance by name (the ServingExperiment
    ``router_policy`` surface)."""
    try:
        return POLICIES[name]()
    except KeyError:
        raise ValueError(
            f"unknown routing policy {name!r}; known: {sorted(POLICIES)}"
        ) from None
