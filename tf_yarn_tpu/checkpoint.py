"""Checkpoint save/restore with latest-step discovery.

The reference delegates checkpointing to TF Estimator / explicit torch
saves with epoch-numbered files and regex discovery (reference:
pytorch/model_ckpt.py:15-73; Estimator `model.ckpt-<step>` parsing in
evaluator_task.py:130-131) — always against a filesystem URL (HDFS via
cluster_pack.filesystem / tf.io.gfile). Here checkpoints are orbax pytrees
in ``<model_dir>/ckpt-<step>`` directories: sharded-array aware (each host
writes its shards — the multi-host story the reference never had) and
discoverable by the same name-parsing convention so the side-car evaluator
can diff "checkpoints on disk" vs "checkpoints evaluated".

``model_dir`` may be a URI (tf_yarn_tpu.fs): discovery, retention GC and
eval markers work on any pyarrow filesystem. The tensor payload has three
paths:

* local / ``file://`` — orbax writes directly;
* ``gs://`` — orbax writes directly (tensorstore speaks GCS);
* any other scheme (``hdfs://``, registered vendor fs) — **staged**: orbax
  writes a local temp dir, the tree is uploaded to
  ``.staging-ckpt-<step>`` (invisible to discovery) and renamed into
  place, so pollers only ever see committed checkpoints. Under multi-host
  the global state is streamed LEAF BY LEAF through
  ``multihost_utils.process_allgather`` and only host 0 (the elected
  uploader) retains the gathered leaves and stages + uploads one
  complete checkpoint — the reference's HDFS ``model_dir`` with
  multi-container jobs (reference: pytorch/model_ckpt.py:31-44,
  tensorflow/tasks/evaluator_task.py:38-51). The full snapshot only ever
  materializes on the uploader: every other host's peak extra RAM is one
  gather batch (<= min(256 MB, a quarter of the tightest host's
  available RAM), plus one leaf if a single leaf exceeds that),
  immediately released. (The allgather still moves each leaf to
  every host — XLA has no gather-to-one-process collective and
  cross-host reshard to a device subset is unsupported outside the TFRT
  TPU runtime — but the *retention* is host-0-only.) Gated on the
  snapshot fitting in the uploader's RAM and the largest leaf fitting
  everywhere; models too big for that need a filesystem orbax can target
  directly (shared mount or gs://).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
import re
import tempfile
import threading
from typing import Any, Dict, List, Optional, Tuple

from tf_yarn_tpu import fs as fs_lib
from tf_yarn_tpu import telemetry
from tf_yarn_tpu.resilience import chaos as _chaos

_logger = logging.getLogger(__name__)


def _observe_op(op: str, seconds: float) -> None:
    """Checkpoint durations land in the process-global registry
    (``checkpoint/seconds{op=...}``) so every run's snapshot carries
    save/restore cost next to the step-time breakdown."""
    telemetry.get_registry().histogram(
        "checkpoint/seconds", op=op
    ).observe(seconds)

_CKPT_RE = re.compile(r"^ckpt-(\d+)$")

# Schemes orbax/tensorstore writes without staging.
_ORBAX_NATIVE_SCHEMES = ("gs",)

# Per-checkpoint integrity manifest: file sizes + checksums, written LAST
# so its presence is the completion marker (docs/Resilience.md). Discovery
# counts only manifested trees; restore verifies against it and
# quarantines mismatches to ckpt-<step>.corrupt.
MANIFEST_NAME = "MANIFEST.json"

# TPU_YARN_CKPT_VERIFY: "sha256" (default) re-hashes every file on
# restore; "size" checks sizes only (cheap safety for multi-GB
# checkpoints on slow links); "off" trusts the bytes.
_VERIFY_ENV = "TPU_YARN_CKPT_VERIFY"


class CheckpointCorrupt(RuntimeError):
    """A checkpoint tree disagrees with its MANIFEST.json (or lacks one
    where required): torn upload, truncated file, bit rot."""


def checkpoint_path(model_dir: str, step: int) -> str:
    return fs_lib.join(model_dir, f"ckpt-{step}")


def _canonicalize_for_save(state: Any) -> Any:
    """Orbax's StandardCheckpointHandler accepts int / float / np.ndarray /
    jax.Array leaves; bare numpy *scalars* (``np.int32(3)`` — e.g. a
    host-side step counter in a TrainState) are rejected by newer orbax.
    Promote them to 0-d ndarrays: dtype preserved, restores as a 0-d
    array every consumer here treats identically. Applied on every save
    entry point so callers never see the orbax type error."""
    import jax
    import numpy as np

    return jax.tree_util.tree_map(
        lambda leaf: np.asarray(leaf) if isinstance(leaf, np.generic) else leaf,
        state,
    )


def list_checkpoint_steps(
    model_dir: str, require_manifest: bool = True
) -> List[int]:
    """All completed checkpoint steps, ascending (reference's regex
    discovery, model_ckpt.py:15-28; works on any fs URI like the
    reference's tf.io.gfile listing, evaluator_task.py:38-51).

    Only *manifested* trees count: the manifest commits last, so a
    half-written `ckpt-<step>` (crash between orbax commit and manifest)
    is invisible to discovery, retention GC and the side-car evaluator
    alike. `require_manifest=False` restores the raw name-match (debris
    inspection, migration tooling)."""
    steps = []
    for name, is_dir in fs_lib.listdir(model_dir):
        match = _CKPT_RE.match(name)
        if not (match and is_dir):
            continue
        if require_manifest and not fs_lib.exists(
            fs_lib.join(model_dir, name, MANIFEST_NAME)
        ):
            continue
        steps.append(int(match.group(1)))
    return sorted(steps)


def latest_checkpoint_step(model_dir: str) -> Optional[int]:
    steps = list_checkpoint_steps(model_dir)
    return steps[-1] if steps else None


# ---------------------------------------------------------------------------
# Manifest write / verify / quarantine
# ---------------------------------------------------------------------------


def _walk_ckpt_files(ckpt_uri: str) -> List[Tuple[str, int]]:
    """Sorted [(relpath, size)] of every file under the tree, manifest
    excluded."""
    from pyarrow import fs as pafs

    filesystem, root = fs_lib.resolve(ckpt_uri)
    selector = pafs.FileSelector(root, recursive=True)
    out: List[Tuple[str, int]] = []
    for info in filesystem.get_file_info(selector):
        if info.type != pafs.FileType.File:
            continue
        rel = info.path[len(root):].lstrip("/")
        if rel == MANIFEST_NAME:
            continue
        out.append((rel, int(info.size or 0)))
    return sorted(out)


def _file_sha256(ckpt_uri: str, rel: str) -> str:
    digest = hashlib.sha256()
    with fs_lib.open_input(fs_lib.join(ckpt_uri, rel)) as stream:
        while True:
            chunk = stream.read(1 << 20)
            if not chunk:
                break
            digest.update(chunk)
    return digest.hexdigest()


def write_manifest(ckpt_uri: str, step: Optional[int] = None) -> Dict:
    """Walk the committed tree and write MANIFEST.json (sizes + sha256).
    This is the LAST write of a save — the completion marker discovery
    keys on."""
    files = {
        rel: {"size": size, "sha256": _file_sha256(ckpt_uri, rel)}
        for rel, size in _walk_ckpt_files(ckpt_uri)
    }
    payload = {"format": 1, "step": step, "files": files}
    fs_lib.write_text(
        fs_lib.join(ckpt_uri, MANIFEST_NAME),
        json.dumps(payload, indent=1, sort_keys=True),
    )
    return payload


def verify_checkpoint(ckpt_uri: str) -> None:
    """Check the tree against its manifest; raises CheckpointCorrupt on
    any disagreement. Depth set by TPU_YARN_CKPT_VERIFY (sha256|size|off)."""
    mode = os.environ.get(_VERIFY_ENV, "sha256").lower()
    if mode == "off":
        return
    manifest_uri = fs_lib.join(ckpt_uri, MANIFEST_NAME)
    if not fs_lib.exists(manifest_uri):
        raise CheckpointCorrupt(f"{ckpt_uri}: no {MANIFEST_NAME}")
    try:
        manifest = json.loads(fs_lib.read_text(manifest_uri))
        expected = manifest["files"]
    except (ValueError, KeyError, TypeError) as exc:
        raise CheckpointCorrupt(
            f"{ckpt_uri}: unparseable {MANIFEST_NAME}: {exc}"
        ) from None
    actual = dict(_walk_ckpt_files(ckpt_uri))
    for rel, meta in expected.items():
        if rel not in actual:
            raise CheckpointCorrupt(f"{ckpt_uri}: missing file {rel!r}")
        if int(meta.get("size", -1)) != actual[rel]:
            raise CheckpointCorrupt(
                f"{ckpt_uri}: size mismatch for {rel!r} "
                f"(manifest {meta.get('size')}, on disk {actual[rel]})"
            )
        if mode == "sha256" and meta.get("sha256"):
            got = _file_sha256(ckpt_uri, rel)
            if got != meta["sha256"]:
                raise CheckpointCorrupt(
                    f"{ckpt_uri}: checksum mismatch for {rel!r}"
                )


def quarantine_checkpoint(model_dir: str, step: int) -> str:
    """Move a corrupt ckpt-<step> aside to ckpt-<step>.corrupt (a name
    discovery never matches) so restore falls back to the previous intact
    step while the evidence survives for a post-mortem."""
    src = checkpoint_path(model_dir, step)
    dst = f"{src}.corrupt"
    fs_lib.rmtree(dst)  # a re-quarantine of the same step replaces
    fs_lib.move(src, dst)
    _logger.error("quarantined corrupt checkpoint %s -> %s", src, dst)
    return dst


def latest_verified_step(model_dir: str) -> Optional[int]:
    """Newest step whose tree passes manifest verification; corrupt trees
    are quarantined on the way down. The resume/discovery entry point —
    the train loop's input-resume step and restore_latest agree through
    this."""
    while True:
        step = latest_checkpoint_step(model_dir)
        if step is None:
            return None
        try:
            verify_checkpoint(checkpoint_path(model_dir, step))
        except CheckpointCorrupt as exc:
            _logger.error(
                "checkpoint verification failed (%s); falling back to the "
                "previous step", exc,
            )
            quarantine_checkpoint(model_dir, step)
            telemetry.get_registry().counter(
                "checkpoint/quarantined_total"
            ).inc()
            continue
        return step


def _is_primary_process() -> bool:
    """One manifest writer under multi-host (every host writes shards into
    the same tree; process 0 stamps it after the collective commit)."""
    try:
        import jax

        return jax.process_index() == 0
    except Exception:  # pragma: no cover - jax-less tooling contexts
        return True


def _commit_manifest(ckpt_uri: str, step: int) -> None:
    """Manifest + chaos commit hook: the shared epilogue of every save
    path, on the elected writer only."""
    if not _is_primary_process():
        return
    write_manifest(ckpt_uri, step=step)
    _chaos.on_checkpoint_commit(ckpt_uri)


def _is_staged(model_dir: str) -> bool:
    scheme = fs_lib.parse_scheme(model_dir)
    return scheme not in ("", "file") and scheme not in _ORBAX_NATIVE_SCHEMES


def _host_available_ram() -> int:
    """Bytes of host memory a staged snapshot may reasonably claim.
    0 = unknown (gate disabled)."""
    try:
        with open("/proc/meminfo") as fh:
            for line in fh:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        return os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    except (ValueError, OSError, AttributeError):
        return 0


def _leaf_nbytes(leaf: Any) -> int:
    """Global byte size of one array leaf (jax.Array .size is the GLOBAL
    element count, so this prices the gathered copy)."""
    size = getattr(leaf, "size", None)
    itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", None)
    if size and itemsize:
        return int(size) * int(itemsize)
    return 0


class PeerStagedFailure(RuntimeError):
    """Raised on hosts that did NOT own a failed background staged upload
    when the owning host reports one — every host leaves the save
    together instead of the owner raising while the rest wedge in the
    gather collective."""


# Per-collective byte budget for the leaf-streaming gather: leaves are
# grouped into batches of up to this many bytes so a state with
# thousands of small leaves (typical optimizer pytrees) doesn't pay one
# cross-host collective per leaf, while a non-uploader's peak retained
# RAM stays bounded by one batch. Tightened further by the agreed
# per-host RAM-derived budget below.
_GATHER_BATCH_BYTES = 256 << 20


def _plan_gather_batches(sized_indices, budget: int):
    """Group (leaf index, nbytes) pairs into batches of <= budget bytes
    each (a single over-budget leaf still forms its own batch — it must
    gather whole). Pure so every host computes identical boundaries."""
    batches: list = []
    current: list = []
    current_bytes = 0
    for index, nbytes in sized_indices:
        if current and current_bytes + nbytes > budget:
            batches.append(current)
            current, current_bytes = [], 0
        current.append(index)
        current_bytes += nbytes
    if current:
        batches.append(current)
    return batches


def _snapshot_for_staging(state: Any, local_error: bool = False):
    """(host-numpy snapshot or None, am_I_the_uploader).

    Single-host: a device_get copy (preserves the train loop's donation
    guarantee — the caller may overwrite device buffers immediately).
    Multi-host: stream the GLOBAL state leaf-by-leaf; only host 0 (the
    elected uploader) keeps the gathered leaves and later stages +
    uploads one complete checkpoint (the reference's HDFS model_dir
    deployment, pytorch/model_ckpt.py:31-44). Every other host returns
    ``(None, False)`` and never holds more than one gathered BATCH
    (budget-bounded, see _GATHER_BATCH_BYTES) at a time. This is a
    collective: every process must call it.

    All divergent decisions are AGREED before anyone enters the first
    leaf gather — a host that raises while its peers enter the
    collective would wedge the job in an allgather instead of failing
    with a message. Three agreed bits:

    * ``local_error`` — the caller (host 0's async writer) has a pending
      upload failure to surface; peers raise PeerStagedFailure so the
      whole fleet leaves save() together.
    * uploader RAM fit — the full snapshot materializes only on host 0,
      so only host 0's RAM must fit it…
    * per-leaf RAM fit — …while every host must briefly fit the largest
      single leaf.
    """
    import jax

    if jax.process_count() > 1:
        import numpy as np
        from jax.experimental import multihost_utils

        uploader = jax.process_index() == 0
        leaves, treedef = jax.tree_util.tree_flatten(state)
        nbytes = sum(_leaf_nbytes(leaf) for leaf in leaves)
        max_leaf = max((_leaf_nbytes(leaf) for leaf in leaves), default=0)
        avail = _host_available_ram()
        need = (nbytes + max_leaf) if uploader else max_leaf
        fits = 0 if (avail and need > avail // 2) else 1
        # Batch budget must be IDENTICAL on every host (different batch
        # boundaries would desynchronize the collectives), so each host
        # offers a RAM-derived cap and the fleet takes the minimum.
        my_budget = _GATHER_BATCH_BYTES
        if avail:
            my_budget = min(my_budget, avail // 4)
        flags = multihost_utils.process_allgather(
            np.array([fits, int(local_error), my_budget], dtype=np.int64))
        all_fit = bool(np.min(flags[..., 0]))
        any_error = bool(np.max(flags[..., 1]))
        batch_budget = int(np.min(flags[..., 2]))
        if any_error:
            if local_error:
                # The caller owns the real exception and re-raises it.
                return None, uploader
            raise PeerStagedFailure(
                "a peer host reported a failed background staged "
                "checkpoint upload; aborting this save everywhere"
            )
        if not all_fit:
            raise ValueError(
                f"staged remote checkpointing gathers the full state "
                f"({nbytes / 1e9:.2f} GB) to the uploader host's RAM "
                f"(largest leaf {max_leaf / 1e9:.2f} GB on every host), "
                f"and at least one host (this one has {avail / 1e9:.2f} "
                "GB available) cannot fit its share. Use a model_dir "
                "orbax can write directly — a shared mount or gs:// — so "
                "each host streams only its own shards."
            )
        gathered: list = [None] * len(leaves)
        gatherable = []
        for i, leaf in enumerate(leaves):
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                gatherable.append((i, _leaf_nbytes(leaf)))
            elif uploader:
                # Host-local leaf (numpy, python scalar, fully-addressable
                # array): process_allgather would CONCATENATE copies along
                # axis 0 / stack scalars, silently corrupting the
                # checkpoint shape on restore — pass the uploader's own
                # value through unchanged instead. Same branch on every
                # host (leaf types are SPMD-identical), so no collective
                # skew.
                gathered[i] = (
                    jax.device_get(leaf)
                    if isinstance(leaf, jax.Array)
                    else leaf
                )
        for batch in _plan_gather_batches(gatherable, batch_budget):
            # tiled=True: reassemble each global array (shards
            # concatenated in place) rather than stacking one copy per
            # process. One collective per batch, not per leaf.
            values = multihost_utils.process_allgather(
                [leaves[i] for i in batch], tiled=True)
            if uploader:
                for i, value in zip(batch, values):
                    gathered[i] = value
            del values  # non-uploaders release each batch immediately
        if not uploader:
            return None, False
        return jax.tree_util.tree_unflatten(treedef, gathered), True
    if local_error:
        # The caller raises the pending upload failure right after this
        # returns — don't build a full host-RAM snapshot just to drop it.
        return None, True
    snapshot = jax.tree_util.tree_map(
        lambda leaf: jax.device_get(leaf)
        if isinstance(leaf, jax.Array)
        else leaf,
        state,
    )
    return snapshot, True


def _local_checkpointer():
    """A StandardCheckpointer whose process coordination spans only THIS
    process: staged saves write a host-local tree from the elected
    uploader while the rest of the world keeps training — barriers over
    the full world would hang (the peers never enter save())."""
    import jax
    import orbax.checkpoint as ocp

    if jax.process_count() == 1:
        return ocp.StandardCheckpointer()
    me = jax.process_index()
    return ocp.StandardCheckpointer(
        multiprocessing_options=ocp.options.MultiprocessingOptions(
            primary_host=me,
            active_processes={me},
            barrier_sync_key_prefix=f"staged-h{me}",
        )
    )


def _orbax_target(model_dir: str, step: int) -> str:
    """The path handed to orbax for a DIRECT (non-staged) save/restore."""
    path = checkpoint_path(model_dir, step)
    if fs_lib.is_local(path):
        return os.path.abspath(fs_lib.local_path(path))
    return path


def _commit_staged(local_ckpt: str, model_dir: str, step: int) -> None:
    """Upload a locally-written ckpt tree and rename it into place.

    The staging name never matches the ckpt-<step> regex, so a polling
    evaluator can't observe a half-uploaded checkpoint."""
    staging = fs_lib.join(model_dir, f".staging-ckpt-{step}")
    final = checkpoint_path(model_dir, step)
    backup = fs_lib.join(model_dir, f".replaced-ckpt-{step}")
    fs_lib.rmtree(staging)
    if fs_lib.exists(backup):
        if fs_lib.exists(final):
            # Crash happened AFTER the replacement committed: the backup
            # is debris.
            fs_lib.rmtree(backup)
        else:
            # Crash happened BETWEEN move-aside and commit: the backup is
            # the only surviving copy of this step — restore it before
            # attempting the new upload (which may itself fail).
            fs_lib.move(backup, final)
    fs_lib.mkdirs(model_dir)
    fs_lib.upload_dir(local_ckpt, staging)
    # Replace a same-step predecessor (force semantics, matching orbax
    # save(force=True)) without a window where neither copy survives: the
    # old tree is moved aside first — a crash mid-commit leaves it under
    # the backup name (plus the fully-uploaded staging tree), never
    # deleted-with-nothing-committed.
    if fs_lib.exists(final):
        fs_lib.move(final, backup)
    fs_lib.move(staging, final)
    fs_lib.rmtree(backup)


def _write_staged(model_dir: str, step: int, snapshot_holder: list) -> None:
    """Serialize a host-numpy snapshot locally and commit it remotely.
    Runs only on the elected uploader (and, for the async writer, on its
    worker thread).

    `snapshot_holder` is a one-element list, emptied once the state is
    on local disk: a bare argument would stay referenced by the
    executor's work item (and the caller's frame) for the whole call, so
    the host-RAM copy would sit pinned through the slow network upload —
    the holder makes the release real, not cosmetic."""
    with telemetry.span("checkpoint/staged_write", step=step) as sp:
        with tempfile.TemporaryDirectory(prefix="tpu-yarn-ckpt-stage-") as tmp:
            local = os.path.join(tmp, f"ckpt-{step}")
            with _local_checkpointer() as ckptr:
                ckptr.save(local, snapshot_holder[0], force=True)
            snapshot_holder.clear()
            # Manifest rides inside the staged tree: the rename-commit
            # publishes payload and completion marker atomically.
            write_manifest(local, step=step)
            _commit_staged(local, model_dir, step)
            _chaos.on_checkpoint_commit(checkpoint_path(model_dir, step))
    _observe_op("staged_write", sp.duration)


def _staged_save(model_dir: str, step: int, state: Any) -> None:
    """Synchronous staged save (collective under multi-host)."""
    snapshot, uploader = _snapshot_for_staging(state)
    if uploader:
        holder = [snapshot]
        del snapshot
        _write_staged(model_dir, step, holder)


@contextlib.contextmanager
def _restorable_path(model_dir: str, step: int):
    """Yield a path orbax can restore from — fetching the tree to a local
    temp dir first when the scheme needs staging."""
    if not _is_staged(model_dir):
        yield _orbax_target(model_dir, step)
        return
    with tempfile.TemporaryDirectory(prefix="tpu-yarn-ckpt-fetch-") as tmp:
        local = os.path.join(tmp, f"ckpt-{step}")
        n = fs_lib.download_dir(checkpoint_path(model_dir, step), local)
        if n == 0:
            raise FileNotFoundError(checkpoint_path(model_dir, step))
        yield local


def save_checkpoint(model_dir: str, step: int, state: Any) -> str:
    """Write `state` (any pytree of arrays) as ckpt-<step>, synchronously.

    The train loop uses CheckpointWriter (async + retention); this stays
    as the simple one-shot API for tools and tests."""
    import orbax.checkpoint as ocp

    path = checkpoint_path(model_dir, step)
    state = _canonicalize_for_save(state)
    with telemetry.span("checkpoint/save", step=step) as sp:
        if _is_staged(model_dir):
            _staged_save(model_dir, step, state)
        else:
            with ocp.StandardCheckpointer() as ckptr:
                ckptr.save(_orbax_target(model_dir, step), state, force=True)
            _commit_manifest(path, step)
    _observe_op("save", sp.duration)
    _logger.info("saved checkpoint %s", path)
    return path


class CheckpointWriter:
    """Async checkpoint writer with keep-last-N retention.

    `save()` blocks only until the state is snapshotted to host memory
    (so the caller may immediately donate/overwrite the device buffers —
    the train loop's `donate_argnums=(0,)` relies on this), then the
    serialization and the directory-rename commit run on background
    threads. Orbax writes into a `.orbax-checkpoint-tmp` staging dir and
    renames on commit, and the MANIFEST.json completion marker (written
    by the finalizer strictly after that commit) is what discovery keys
    on — so a concurrently polling side-car evaluator (evaluation.py)
    only ever sees completed, integrity-stamped checkpoints. The same
    holds on staged-remote filesystems via `.staging-ckpt-<step>` upload
    + rename (the manifest rides inside the staged tree).

    Retention: before each save, completed `ckpt-*` dirs beyond the
    newest `keep_last_n` are deleted (the Estimator-style keep_max
    semantics the reference relied on; VERDICT r1 item 3). Only process 0
    garbage-collects under multi-host — every host writes shards into the
    same directory tree, so one deleter suffices.
    """

    def __init__(self, keep_last_n: Optional[int] = None):
        import orbax.checkpoint as ocp

        self.keep_last_n = keep_last_n
        self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        self._executor = None  # staged-upload worker, created on demand
        self._finalizer = None  # manifest writer for async direct saves
        self._staged_futures: list = []
        # Guards _staged_futures only: appended by the train thread,
        # drained by wait()/close() — close() may run on a different
        # thread (driver shutdown), and an unguarded list rebind there
        # can drop futures or re-raise a settled error (found by the
        # TYA311 lockset scenario suite). Never held while blocking on
        # a future: the worker threads never take it, so ordering is
        # deadlock-free by construction.
        self._staged_lock = threading.Lock()
        self._last_submitted: Optional[Tuple[str, int]] = None
        # Serializes every _ckptr interaction: orbax's AsyncManager
        # .wait_until_finished is check-then-join on its worker-thread
        # attr, so the train thread (save(force=True) waits internally)
        # racing the manifest finalizer's wait could join a thread the
        # other caller just nulled (AttributeError: 'NoneType'.join —
        # seen as a rare tier-1 flake under full-suite load).
        self._ckptr_lock = threading.Lock()

    def save(self, model_dir: str, step: int, state: Any) -> str:
        import orbax.checkpoint as ocp

        # save_submit prices only the blocking part (host snapshot /
        # async enqueue) — the part the train loop actually stalls on;
        # the background serialization shows up as staged_write / wait.
        with telemetry.span("checkpoint/save_submit", step=step) as sp:
            if (model_dir, step) == self._last_submitted:
                # Re-save of the SAME tree: the previous save's commit +
                # manifest must fully land first — orbax replaces the
                # directory, and the earlier save's finalizer caught
                # mid-hash would read files the replace just deleted.
                # Wait without consuming errors (they surface through
                # the normal save/wait paths, where multi-host raising
                # is coordinated).
                import concurrent.futures

                with self._ckptr_lock:
                    self._ckptr.wait_until_finished()
                with self._staged_lock:
                    staged = list(self._staged_futures)
                concurrent.futures.wait(staged)
            self._last_submitted = (model_dir, step)
            self._gc(model_dir)
            state = _canonicalize_for_save(state)
            path = checkpoint_path(model_dir, step)
            if _is_staged(model_dir):
                self._staged_async_save(model_dir, step, state)
            else:
                # Under the lock: save(force=True) internally waits for
                # the previous save, which must not race the finalizer
                # thread's own wait (see _ckptr_lock).
                with self._ckptr_lock:
                    self._ckptr.save(
                        _orbax_target(model_dir, step),
                        args=ocp.args.StandardSave(state),
                        force=True,
                    )
                self._submit_finalize(model_dir, step)
        _observe_op("save_submit", sp.duration)
        _logger.info("checkpoint %s save started (async)", path)
        return path

    def _submit_finalize(self, model_dir: str, step: int) -> None:
        """Queue the manifest write to land strictly after orbax's async
        commit — the manifest is the completion marker, so it cannot be
        written from save() (the payload is still in flight). A dedicated
        single worker keeps finalizations ordered; its failures surface
        through the same once-only queue as staged-upload errors."""
        import concurrent.futures

        if self._finalizer is None:
            self._finalizer = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-manifest"
            )
        future = self._finalizer.submit(
            self._finalize_direct, model_dir, step
        )
        with self._staged_lock:
            self._staged_futures.append(future)

    def _finalize_direct(self, model_dir: str, step: int) -> None:
        # Blocks until every in-flight orbax save (>= this step) has
        # committed; a manifest written later than strictly necessary is
        # fine, one written earlier would mark an incomplete tree.
        with self._ckptr_lock:
            self._ckptr.wait_until_finished()
        _commit_manifest(checkpoint_path(model_dir, step), step)

    def _staged_async_save(self, model_dir: str, step: int, state: Any) -> None:
        """Snapshot to host now (preserving the donation guarantee), then
        serialize + upload + rename on the worker thread. Collective
        under multi-host: every process gathers, host 0 uploads."""
        import concurrent.futures

        # Backpressure: at most one upload in flight. Each snapshot pins a
        # full host-RAM copy of the state; letting them queue behind a
        # slow link would grow memory without bound. The error is only
        # COLLECTED here — raising before the collective would leave the
        # peers wedged in the gather; _snapshot_for_staging agrees the
        # error bit across hosts so everyone aborts together, then the
        # owning host re-raises the real exception.
        pending = self._collect_staged_errors(block=True)
        snapshot, uploader = _snapshot_for_staging(
            state, local_error=pending is not None)
        if pending is not None:
            raise pending
        if not uploader:
            return
        holder = [snapshot]
        del snapshot
        if self._executor is None:
            self._executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-stage"
            )
        future = self._executor.submit(
            _write_staged, model_dir, step, holder
        )
        with self._staged_lock:
            self._staged_futures.append(future)

    def _collect_staged_errors(self, block: bool):
        """First failure of a background staged save, or None. Settled
        futures leave the queue even when failing, so one failure is
        reported once — not re-surfaced by every later call."""
        with self._staged_lock:
            futures, self._staged_futures = self._staged_futures, []
        pending, errors = [], []
        for future in futures:
            if block or future.done():
                exc = future.exception()  # waits when block=True
                if exc is not None:
                    errors.append(exc)
            else:
                pending.append(future)
        if pending:
            # Futures submitted while we were draining stay queued; ours
            # go back in front to preserve submission order.
            with self._staged_lock:
                self._staged_futures[:0] = pending
        return errors[0] if errors else None

    def _raise_staged_errors(self, block: bool) -> None:
        """Surface failures of background staged saves to the caller (an
        upload failure from save(N) raises from the next save()/wait()).
        Only for non-collective call sites (wait/close) — inside save()
        the error must be agreed across hosts first (_staged_async_save)."""
        exc = self._collect_staged_errors(block)
        if exc is not None:
            raise exc

    def _gc(self, model_dir: str) -> None:
        """Best-effort retention: _gc runs on process 0 only, directly
        before save()'s collective (the gather agreement / the orbax
        async save), so a raise here would diverge host 0 from its peers
        and wedge the fleet in the collective. A transient remote-fs
        error just defers the deletion to the next save."""
        if not self.keep_last_n:
            return
        import jax

        if jax.process_index() != 0:
            return
        try:
            # Only completed checkpoints are listed, so an in-flight save
            # can never be collected out from under its commit.
            steps = list_checkpoint_steps(model_dir)
            for step in steps[: -self.keep_last_n]:
                path = checkpoint_path(model_dir, step)
                _logger.info(
                    "retention(%d): deleting %s", self.keep_last_n, path)
                fs_lib.rmtree(path)
        except Exception:
            _logger.warning(
                "retention GC failed for %s; will retry on the next save",
                model_dir, exc_info=True,
            )

    def wait(self) -> None:
        """Block until every started save has committed."""
        with telemetry.span("checkpoint/wait") as sp:
            with self._ckptr_lock:
                self._ckptr.wait_until_finished()
            self._raise_staged_errors(block=True)
        _observe_op("wait", sp.duration)

    def close(self) -> None:
        # Drain background work BEFORE closing the checkpointer — the
        # manifest finalizer waits on it and must not find it closed.
        if self._finalizer is not None:
            self._finalizer.shutdown(wait=True)
        if self._executor is not None:
            self._executor.shutdown(wait=True)
        with self._ckptr_lock:
            self._ckptr.close()
        self._raise_staged_errors(block=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def restore_checkpoint(model_dir: str, step: int, target: Optional[Any] = None) -> Any:
    """Restore ckpt-<step>; `target` (a pytree of like-shaped arrays or
    ShapeDtypeStructs with shardings) directs placement on restore."""
    import orbax.checkpoint as ocp

    with telemetry.span("checkpoint/restore", step=step) as sp:
        with _restorable_path(model_dir, step) as path:
            with ocp.StandardCheckpointer() as ckptr:
                if target is None:
                    restored = ckptr.restore(path)
                else:
                    import jax

                    abstract = jax.tree_util.tree_map(
                        ocp.utils.to_shape_dtype_struct, target
                    )
                    restored = ckptr.restore(path, abstract)
    _observe_op("restore", sp.duration)
    return restored


def restore_checkpoint_host(model_dir: str, step: int) -> Any:
    """Restore ckpt-<step> as plain numpy on the host, regardless of the
    device topology it was saved under (the side-car evaluator restores
    8-mesh checkpoints on its single CPU device this way)."""
    import jax
    import numpy as np
    import orbax.checkpoint as ocp

    with telemetry.span("checkpoint/restore_host", step=step) as sp:
        with _restorable_path(model_dir, step) as path:
            with ocp.PyTreeCheckpointer() as ckptr:
                # Orbax API drift: metadata() returns the metadata tree
                # directly on some versions, an object carrying it as
                # .item_metadata (possibly wrapped in .tree) on others.
                meta = ckptr.metadata(path)
                item = getattr(meta, "item_metadata", None)
                if item is None:
                    item = meta
                tree = getattr(item, "tree", item)  # dict of ArrayMetadata leaves
                restore_args = jax.tree_util.tree_map(
                    lambda _: ocp.RestoreArgs(restore_type=np.ndarray), tree
                )
                restored = ckptr.restore(path, restore_args=restore_args)
    _observe_op("restore_host", sp.duration)
    return restored


def restore_latest(model_dir: str, target: Optional[Any] = None):
    """(state, step) of the newest *verified* checkpoint, or (None, None) —
    the resume path the retry loop relies on (reference resumes from
    model_dir, SURVEY.md §5 checkpoint/resume).

    Every candidate is checked against its MANIFEST.json first; a tree
    that fails verification is quarantined to ``ckpt-<step>.corrupt`` and
    the previous intact step restores instead — resuming from a torn
    checkpoint would silently train on garbage (or crash deep inside
    orbax with no cause attached)."""
    step = latest_verified_step(model_dir)
    if step is None:
        return None, None
    return restore_checkpoint(model_dir, step, target), step
