"""Checkpoint save/restore with latest-step discovery.

The reference delegates checkpointing to TF Estimator / explicit torch
saves with epoch-numbered files and regex discovery (reference:
pytorch/model_ckpt.py:15-73; Estimator `model.ckpt-<step>` parsing in
evaluator_task.py:130-131). Here checkpoints are orbax pytrees in
``<model_dir>/ckpt-<step>`` directories: sharded-array aware (each host
writes its shards — the multi-host story the reference never had) and
discoverable by the same name-parsing convention so the side-car evaluator
can diff "checkpoints on disk" vs "checkpoints evaluated".
"""

from __future__ import annotations

import logging
import os
import re
from typing import Any, List, Optional

_logger = logging.getLogger(__name__)

_CKPT_RE = re.compile(r"^ckpt-(\d+)$")


def checkpoint_path(model_dir: str, step: int) -> str:
    return os.path.join(model_dir, f"ckpt-{step}")


def list_checkpoint_steps(model_dir: str) -> List[int]:
    """All completed checkpoint steps, ascending (reference's regex
    discovery, model_ckpt.py:15-28)."""
    if not os.path.isdir(model_dir):
        return []
    steps = []
    for entry in os.listdir(model_dir):
        match = _CKPT_RE.match(entry)
        if match and os.path.isdir(os.path.join(model_dir, entry)):
            steps.append(int(match.group(1)))
    return sorted(steps)


def latest_checkpoint_step(model_dir: str) -> Optional[int]:
    steps = list_checkpoint_steps(model_dir)
    return steps[-1] if steps else None


def save_checkpoint(model_dir: str, step: int, state: Any) -> str:
    """Write `state` (any pytree of arrays) as ckpt-<step>."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(checkpoint_path(model_dir, step))
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state, force=True)
    _logger.info("saved checkpoint %s", path)
    return path


def restore_checkpoint(model_dir: str, step: int, target: Optional[Any] = None) -> Any:
    """Restore ckpt-<step>; `target` (a pytree of like-shaped arrays or
    ShapeDtypeStructs with shardings) directs placement on restore."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(checkpoint_path(model_dir, step))
    with ocp.StandardCheckpointer() as ckptr:
        if target is None:
            return ckptr.restore(path)
        import jax

        abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, target)
        return ckptr.restore(path, abstract)


def restore_checkpoint_host(model_dir: str, step: int) -> Any:
    """Restore ckpt-<step> as plain numpy on the host, regardless of the
    device topology it was saved under (the side-car evaluator restores
    8-mesh checkpoints on its single CPU device this way)."""
    import jax
    import numpy as np
    import orbax.checkpoint as ocp

    path = os.path.abspath(checkpoint_path(model_dir, step))
    with ocp.PyTreeCheckpointer() as ckptr:
        item = ckptr.metadata(path).item_metadata
        tree = getattr(item, "tree", item)  # dict of ArrayMetadata leaves
        restore_args = jax.tree_util.tree_map(
            lambda _: ocp.RestoreArgs(restore_type=np.ndarray), tree
        )
        return ckptr.restore(path, restore_args=restore_args)


def restore_latest(model_dir: str, target: Optional[Any] = None):
    """(state, step) of the newest checkpoint, or (None, None) — the resume
    path the retry loop relies on (reference resumes from model_dir,
    SURVEY.md §5 checkpoint/resume)."""
    step = latest_checkpoint_step(model_dir)
    if step is None:
        return None, None
    return restore_checkpoint(model_dir, step, target), step
