"""Checkpoint save/restore with latest-step discovery.

The reference delegates checkpointing to TF Estimator / explicit torch
saves with epoch-numbered files and regex discovery (reference:
pytorch/model_ckpt.py:15-73; Estimator `model.ckpt-<step>` parsing in
evaluator_task.py:130-131). Here checkpoints are orbax pytrees in
``<model_dir>/ckpt-<step>`` directories: sharded-array aware (each host
writes its shards — the multi-host story the reference never had) and
discoverable by the same name-parsing convention so the side-car evaluator
can diff "checkpoints on disk" vs "checkpoints evaluated".
"""

from __future__ import annotations

import logging
import os
import re
from typing import Any, List, Optional

_logger = logging.getLogger(__name__)

_CKPT_RE = re.compile(r"^ckpt-(\d+)$")


def checkpoint_path(model_dir: str, step: int) -> str:
    return os.path.join(model_dir, f"ckpt-{step}")


def list_checkpoint_steps(model_dir: str) -> List[int]:
    """All completed checkpoint steps, ascending (reference's regex
    discovery, model_ckpt.py:15-28)."""
    if not os.path.isdir(model_dir):
        return []
    steps = []
    for entry in os.listdir(model_dir):
        match = _CKPT_RE.match(entry)
        if match and os.path.isdir(os.path.join(model_dir, entry)):
            steps.append(int(match.group(1)))
    return sorted(steps)


def latest_checkpoint_step(model_dir: str) -> Optional[int]:
    steps = list_checkpoint_steps(model_dir)
    return steps[-1] if steps else None


def save_checkpoint(model_dir: str, step: int, state: Any) -> str:
    """Write `state` (any pytree of arrays) as ckpt-<step>, synchronously.

    The train loop uses CheckpointWriter (async + retention); this stays
    as the simple one-shot API for tools and tests."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(checkpoint_path(model_dir, step))
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state, force=True)
    _logger.info("saved checkpoint %s", path)
    return path


class CheckpointWriter:
    """Async checkpoint writer with keep-last-N retention.

    `save()` blocks only until the state is snapshotted to host memory
    (so the caller may immediately donate/overwrite the device buffers —
    the train loop's `donate_argnums=(0,)` relies on this), then the
    serialization and the directory-rename commit run on background
    threads. Orbax writes into a `.orbax-checkpoint-tmp` staging dir and
    renames on commit, and `list_checkpoint_steps`'s `ckpt-<step>` regex
    never matches staging names — so a concurrently polling side-car
    evaluator (evaluation.py) only ever sees completed checkpoints.

    Retention: before each save, completed `ckpt-*` dirs beyond the
    newest `keep_last_n` are deleted (the Estimator-style keep_max
    semantics the reference relied on; VERDICT r1 item 3). Only process 0
    garbage-collects under multi-host — every host writes shards into the
    same directory tree, so one deleter suffices.
    """

    def __init__(self, keep_last_n: Optional[int] = None):
        import orbax.checkpoint as ocp

        self.keep_last_n = keep_last_n
        self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())

    def save(self, model_dir: str, step: int, state: Any) -> str:
        import orbax.checkpoint as ocp

        self._gc(model_dir)
        path = os.path.abspath(checkpoint_path(model_dir, step))
        self._ckptr.save(
            path, args=ocp.args.StandardSave(state), force=True
        )
        _logger.info("checkpoint %s save started (async)", path)
        return path

    def _gc(self, model_dir: str) -> None:
        if not self.keep_last_n:
            return
        import jax

        if jax.process_index() != 0:
            return
        import shutil

        # Only completed checkpoints are listed, so an in-flight save can
        # never be collected out from under its commit.
        steps = list_checkpoint_steps(model_dir)
        for step in steps[: -self.keep_last_n]:
            path = checkpoint_path(model_dir, step)
            _logger.info("retention(%d): deleting %s", self.keep_last_n, path)
            shutil.rmtree(path, ignore_errors=True)

    def wait(self) -> None:
        """Block until every started save has committed."""
        self._ckptr.wait_until_finished()

    def close(self) -> None:
        self._ckptr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def restore_checkpoint(model_dir: str, step: int, target: Optional[Any] = None) -> Any:
    """Restore ckpt-<step>; `target` (a pytree of like-shaped arrays or
    ShapeDtypeStructs with shardings) directs placement on restore."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(checkpoint_path(model_dir, step))
    with ocp.StandardCheckpointer() as ckptr:
        if target is None:
            return ckptr.restore(path)
        import jax

        abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, target)
        return ckptr.restore(path, abstract)


def restore_checkpoint_host(model_dir: str, step: int) -> Any:
    """Restore ckpt-<step> as plain numpy on the host, regardless of the
    device topology it was saved under (the side-car evaluator restores
    8-mesh checkpoints on its single CPU device this way)."""
    import jax
    import numpy as np
    import orbax.checkpoint as ocp

    path = os.path.abspath(checkpoint_path(model_dir, step))
    with ocp.PyTreeCheckpointer() as ckptr:
        item = ckptr.metadata(path).item_metadata
        tree = getattr(item, "tree", item)  # dict of ArrayMetadata leaves
        restore_args = jax.tree_util.tree_map(
            lambda _: ocp.RestoreArgs(restore_type=np.ndarray), tree
        )
        return ckptr.restore(path, restore_args=restore_args)


def restore_latest(model_dir: str, target: Optional[Any] = None):
    """(state, step) of the newest checkpoint, or (None, None) — the resume
    path the retry loop relies on (reference resumes from model_dir,
    SURVEY.md §5 checkpoint/resume)."""
    step = latest_checkpoint_step(model_dir)
    if step is None:
        return None, None
    return restore_checkpoint(model_dir, step, target), step
