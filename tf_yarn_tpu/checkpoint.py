"""Checkpoint save/restore with latest-step discovery.

The reference delegates checkpointing to TF Estimator / explicit torch
saves with epoch-numbered files and regex discovery (reference:
pytorch/model_ckpt.py:15-73; Estimator `model.ckpt-<step>` parsing in
evaluator_task.py:130-131) — always against a filesystem URL (HDFS via
cluster_pack.filesystem / tf.io.gfile). Here checkpoints are orbax pytrees
in ``<model_dir>/ckpt-<step>`` directories: sharded-array aware (each host
writes its shards — the multi-host story the reference never had) and
discoverable by the same name-parsing convention so the side-car evaluator
can diff "checkpoints on disk" vs "checkpoints evaluated".

``model_dir`` may be a URI (tf_yarn_tpu.fs): discovery, retention GC and
eval markers work on any pyarrow filesystem. The tensor payload has three
paths:

* local / ``file://`` — orbax writes directly;
* ``gs://`` — orbax writes directly (tensorstore speaks GCS);
* any other scheme (``hdfs://``, registered vendor fs) — **staged**: orbax
  writes a local temp dir, the tree is uploaded to
  ``.staging-ckpt-<step>`` (invisible to discovery) and renamed into
  place, so pollers only ever see committed checkpoints. Staged mode is
  single-host only: multi-host jobs write shards from every process and
  need a filesystem orbax can target directly (shared mount or gs://).
"""

from __future__ import annotations

import contextlib
import logging
import os
import re
import tempfile
from typing import Any, List, Optional

from tf_yarn_tpu import fs as fs_lib

_logger = logging.getLogger(__name__)

_CKPT_RE = re.compile(r"^ckpt-(\d+)$")

# Schemes orbax/tensorstore writes without staging.
_ORBAX_NATIVE_SCHEMES = ("gs",)


def checkpoint_path(model_dir: str, step: int) -> str:
    return fs_lib.join(model_dir, f"ckpt-{step}")


def list_checkpoint_steps(model_dir: str) -> List[int]:
    """All completed checkpoint steps, ascending (reference's regex
    discovery, model_ckpt.py:15-28; works on any fs URI like the
    reference's tf.io.gfile listing, evaluator_task.py:38-51)."""
    steps = []
    for name, is_dir in fs_lib.listdir(model_dir):
        match = _CKPT_RE.match(name)
        if match and is_dir:
            steps.append(int(match.group(1)))
    return sorted(steps)


def latest_checkpoint_step(model_dir: str) -> Optional[int]:
    steps = list_checkpoint_steps(model_dir)
    return steps[-1] if steps else None


def _is_staged(model_dir: str) -> bool:
    scheme = fs_lib.parse_scheme(model_dir)
    return scheme not in ("", "file") and scheme not in _ORBAX_NATIVE_SCHEMES


def _require_single_host(what: str) -> None:
    import jax

    if jax.process_count() > 1:
        raise ValueError(
            f"{what} is single-host only: every process writes its own "
            "array shards, and staging-then-uploading per host would "
            "scatter one checkpoint across machines. Multi-host jobs need "
            "a model_dir orbax can write directly — a shared mount or "
            "gs://."
        )


def _orbax_target(model_dir: str, step: int) -> str:
    """The path handed to orbax for a DIRECT (non-staged) save/restore."""
    path = checkpoint_path(model_dir, step)
    if fs_lib.is_local(path):
        return os.path.abspath(fs_lib.local_path(path))
    return path


def _commit_staged(local_ckpt: str, model_dir: str, step: int) -> None:
    """Upload a locally-written ckpt tree and rename it into place.

    The staging name never matches the ckpt-<step> regex, so a polling
    evaluator can't observe a half-uploaded checkpoint."""
    staging = fs_lib.join(model_dir, f".staging-ckpt-{step}")
    final = checkpoint_path(model_dir, step)
    fs_lib.rmtree(staging)
    fs_lib.mkdirs(model_dir)
    fs_lib.upload_dir(local_ckpt, staging)
    # Delete a same-step predecessor only once its replacement is fully
    # uploaded (force semantics, matching orbax save(force=True)) — an
    # upload failure must never cost the last good checkpoint.
    fs_lib.rmtree(final)
    fs_lib.move(staging, final)


def _staged_save(model_dir: str, step: int, state: Any) -> None:
    import orbax.checkpoint as ocp

    _require_single_host("staged remote checkpointing")
    with tempfile.TemporaryDirectory(prefix="tpu-yarn-ckpt-stage-") as tmp:
        local = os.path.join(tmp, f"ckpt-{step}")
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(local, state, force=True)
        _commit_staged(local, model_dir, step)


@contextlib.contextmanager
def _restorable_path(model_dir: str, step: int):
    """Yield a path orbax can restore from — fetching the tree to a local
    temp dir first when the scheme needs staging."""
    if not _is_staged(model_dir):
        yield _orbax_target(model_dir, step)
        return
    with tempfile.TemporaryDirectory(prefix="tpu-yarn-ckpt-fetch-") as tmp:
        local = os.path.join(tmp, f"ckpt-{step}")
        n = fs_lib.download_dir(checkpoint_path(model_dir, step), local)
        if n == 0:
            raise FileNotFoundError(checkpoint_path(model_dir, step))
        yield local


def save_checkpoint(model_dir: str, step: int, state: Any) -> str:
    """Write `state` (any pytree of arrays) as ckpt-<step>, synchronously.

    The train loop uses CheckpointWriter (async + retention); this stays
    as the simple one-shot API for tools and tests."""
    import orbax.checkpoint as ocp

    path = checkpoint_path(model_dir, step)
    if _is_staged(model_dir):
        _staged_save(model_dir, step, state)
    else:
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(_orbax_target(model_dir, step), state, force=True)
    _logger.info("saved checkpoint %s", path)
    return path


class CheckpointWriter:
    """Async checkpoint writer with keep-last-N retention.

    `save()` blocks only until the state is snapshotted to host memory
    (so the caller may immediately donate/overwrite the device buffers —
    the train loop's `donate_argnums=(0,)` relies on this), then the
    serialization and the directory-rename commit run on background
    threads. Orbax writes into a `.orbax-checkpoint-tmp` staging dir and
    renames on commit, and `list_checkpoint_steps`'s `ckpt-<step>` regex
    never matches staging names — so a concurrently polling side-car
    evaluator (evaluation.py) only ever sees completed checkpoints. The
    same holds on staged-remote filesystems via `.staging-ckpt-<step>`
    upload + rename.

    Retention: before each save, completed `ckpt-*` dirs beyond the
    newest `keep_last_n` are deleted (the Estimator-style keep_max
    semantics the reference relied on; VERDICT r1 item 3). Only process 0
    garbage-collects under multi-host — every host writes shards into the
    same directory tree, so one deleter suffices.
    """

    def __init__(self, keep_last_n: Optional[int] = None):
        import orbax.checkpoint as ocp

        self.keep_last_n = keep_last_n
        self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        self._executor = None  # staged-upload worker, created on demand
        self._staged_futures: list = []

    def save(self, model_dir: str, step: int, state: Any) -> str:
        import orbax.checkpoint as ocp

        self._gc(model_dir)
        path = checkpoint_path(model_dir, step)
        if _is_staged(model_dir):
            self._staged_async_save(model_dir, step, state)
        else:
            self._ckptr.save(
                _orbax_target(model_dir, step),
                args=ocp.args.StandardSave(state),
                force=True,
            )
        _logger.info("checkpoint %s save started (async)", path)
        return path

    def _staged_async_save(self, model_dir: str, step: int, state: Any) -> None:
        """Snapshot to host now (preserving the donation guarantee), then
        serialize + upload + rename on the worker thread."""
        import concurrent.futures

        import jax

        _require_single_host("staged remote checkpointing")
        # Backpressure: at most one upload in flight. Each snapshot pins a
        # full host-RAM copy of the state; letting them queue behind a
        # slow link would grow memory without bound.
        self._raise_staged_errors(block=True)
        snapshot = jax.tree_util.tree_map(
            lambda leaf: jax.device_get(leaf)
            if isinstance(leaf, jax.Array)
            else leaf,
            state,
        )
        if self._executor is None:
            self._executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-stage"
            )
        self._staged_futures.append(
            self._executor.submit(_staged_save, model_dir, step, snapshot)
        )

    def _raise_staged_errors(self, block: bool) -> None:
        pending = []
        for future in self._staged_futures:
            if block or future.done():
                future.result()  # re-raises upload failures
            else:
                pending.append(future)
        self._staged_futures = pending

    def _gc(self, model_dir: str) -> None:
        if not self.keep_last_n:
            return
        import jax

        if jax.process_index() != 0:
            return
        # Only completed checkpoints are listed, so an in-flight save can
        # never be collected out from under its commit.
        steps = list_checkpoint_steps(model_dir)
        for step in steps[: -self.keep_last_n]:
            path = checkpoint_path(model_dir, step)
            _logger.info("retention(%d): deleting %s", self.keep_last_n, path)
            fs_lib.rmtree(path)

    def wait(self) -> None:
        """Block until every started save has committed."""
        self._ckptr.wait_until_finished()
        self._raise_staged_errors(block=True)

    def close(self) -> None:
        self._ckptr.close()
        if self._executor is not None:
            self._executor.shutdown(wait=True)
            self._raise_staged_errors(block=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def restore_checkpoint(model_dir: str, step: int, target: Optional[Any] = None) -> Any:
    """Restore ckpt-<step>; `target` (a pytree of like-shaped arrays or
    ShapeDtypeStructs with shardings) directs placement on restore."""
    import orbax.checkpoint as ocp

    with _restorable_path(model_dir, step) as path:
        with ocp.StandardCheckpointer() as ckptr:
            if target is None:
                return ckptr.restore(path)
            import jax

            abstract = jax.tree_util.tree_map(
                ocp.utils.to_shape_dtype_struct, target
            )
            return ckptr.restore(path, abstract)


def restore_checkpoint_host(model_dir: str, step: int) -> Any:
    """Restore ckpt-<step> as plain numpy on the host, regardless of the
    device topology it was saved under (the side-car evaluator restores
    8-mesh checkpoints on its single CPU device this way)."""
    import jax
    import numpy as np
    import orbax.checkpoint as ocp

    with _restorable_path(model_dir, step) as path:
        with ocp.PyTreeCheckpointer() as ckptr:
            item = ckptr.metadata(path).item_metadata
            tree = getattr(item, "tree", item)  # dict of ArrayMetadata leaves
            restore_args = jax.tree_util.tree_map(
                lambda _: ocp.RestoreArgs(restore_type=np.ndarray), tree
            )
            return ckptr.restore(path, restore_args=restore_args)


def restore_latest(model_dir: str, target: Optional[Any] = None):
    """(state, step) of the newest checkpoint, or (None, None) — the resume
    path the retry loop relies on (reference resumes from model_dir,
    SURVEY.md §5 checkpoint/resume)."""
    step = latest_checkpoint_step(model_dir)
    if step is None:
        return None, None
    return restore_checkpoint(model_dir, step, target), step
