"""Continuous (side-car) evaluation.

Placeholder for the checkpoint-polling evaluator loop (reference:
tensorflow/tasks/evaluator_task.py:18-158) landing with the checkpoint
subsystem; for now the side-car simply keeps pace with the training tasks.
"""

from __future__ import annotations

import logging

from tf_yarn_tpu.tasks import _bootstrap

_logger = logging.getLogger(__name__)


def continuous_eval(runtime: _bootstrap.TaskRuntime, experiment) -> None:
    _logger.warning(
        "checkpoint-polling evaluation not yet implemented; waiting for "
        "training tasks to finish"
    )
    _bootstrap.wait_for_all_stops(runtime)
