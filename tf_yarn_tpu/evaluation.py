"""Continuous (side-car) evaluation over the checkpoint stream.

Port of the reference's evaluator loop (reference: tensorflow/tasks/
evaluator_task.py:18-158): poll the experiment's model_dir, evaluate every
checkpoint exactly once, stop when the final-step checkpoint is done or
nothing new has appeared for the idle timeout. Evaluated-set persistence
uses `eval-done-<step>.json` marker files next to the checkpoints — the
role the reference's tf-events parsing plays (evaluator_task.py:46-51,
tensorflow/metrics.py:74-100) without a TF dependency.

Health metrics broadcast to the KV store match the reference's monitored
set (evaluator_metrics.py:12-17): awake_time_ratio,
eval_step_mean_duration, last_training_step, nb_eval_steps — polled and
logged driver-side by utils.evaluator_metrics.EvaluatorMetricsLogger.

Placement: the evaluator is a CPU task (SURVEY.md §7 hard part 5 — TPU
hosts are symmetric, so the driver pins TPU_YARN_PLATFORM=cpu in its env).
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Optional, Set

import jax

from tf_yarn_tpu import checkpoint as ckpt_lib
from tf_yarn_tpu import event
from tf_yarn_tpu import fs as fs_lib
from tf_yarn_tpu.experiment import as_core_experiment
from tf_yarn_tpu.tasks import _bootstrap
from tf_yarn_tpu.training import build_eval_step, evaluate
from tf_yarn_tpu.utils import mlflow

_logger = logging.getLogger(__name__)

DEFAULT_IDLE_TIMEOUT_SECS = 20 * 60  # reference: evaluator_task.py:21-23
DEFAULT_POLL_SECS = 10.0


EVAL_DONE_DIR = "eval-done"  # bookkeeping lives out of checkpoint listings


def _marker_steps(directory: str) -> Set[int]:
    done: Set[int] = set()
    for entry, _is_dir in fs_lib.listdir(directory):
        if entry.startswith("eval-done-") and entry.endswith(".json"):
            try:
                done.add(int(entry[len("eval-done-"):-len(".json")]))
            except ValueError:
                continue
    return done


def _evaluated_steps(model_dir: str) -> Set[int]:
    # Markers written before the subdirectory move lived at the model_dir
    # root; honor both so resuming against an old run doesn't re-evaluate
    # (and re-emit metrics for) every checkpoint. model_dir may be any fs
    # URI (the reference lists its HDFS model_dir the same way,
    # evaluator_task.py:38-51).
    return _marker_steps(fs_lib.join(model_dir, EVAL_DONE_DIR)) | _marker_steps(
        model_dir
    )


def _mark_evaluated(model_dir: str, step: int, metrics: dict) -> None:
    marker = fs_lib.join(model_dir, EVAL_DONE_DIR, f"eval-done-{step}.json")
    fs_lib.write_text(marker, json.dumps(metrics))


def evaluate_checkpoint(
    model, loss_fn, model_dir: str, step: int, eval_input_fn, eval_steps: int,
    rng_seed: int = 0,
) -> dict:
    """Host-restore ckpt-<step> and evaluate it on `eval_input_fn`
    (Estimator.evaluate's one-shot path; the side-car loop keeps its own
    copy with a pre-built jitted eval_step so repeated checkpoints reuse
    one compilation)."""
    from tf_yarn_tpu.training import TrainState, build_eval_step, evaluate

    state = ckpt_lib.restore_checkpoint_host(model_dir, step)
    params = state["params"] if isinstance(state, dict) else state.params
    eval_state = TrainState(step=0, params=params, opt_state=())
    eval_step = jax.jit(build_eval_step(model, loss_fn))
    return evaluate(
        eval_step, eval_state, eval_input_fn, lambda b: b, eval_steps,
        jax.random.PRNGKey(rng_seed),
    )


def continuous_eval(
    runtime: Optional[_bootstrap.TaskRuntime],
    experiment,
    poll_secs: float = DEFAULT_POLL_SECS,
    idle_timeout_secs: Optional[float] = None,
) -> dict:
    """Evaluate checkpoints as they appear; returns last metrics."""
    if idle_timeout_secs is None:
        idle_timeout_secs = float(
            os.environ.get("TPU_YARN_EVAL_IDLE_TIMEOUT", DEFAULT_IDLE_TIMEOUT_SECS)
        )
    platform = os.environ.get("TPU_YARN_PLATFORM")
    if platform:  # evaluator is a CPU side-car; don't touch the slice's chips
        try:
            jax.config.update("jax_platforms", platform)
        except Exception:  # pragma: no cover - backends already initialized
            _logger.debug("jax_platforms narrowing skipped", exc_info=True)
    core = as_core_experiment(experiment)
    if not core.model_dir:
        raise ValueError("continuous evaluation needs an experiment model_dir")
    fs_lib.check_model_dir_placement(core.model_dir)
    eval_input_fn = core.eval_input_fn or core.train_input_fn
    eval_step = jax.jit(build_eval_step(core.model, core.loss_fn))
    rng = jax.random.PRNGKey(core.train_params.seed)

    # One callable or a sequence of them (the reference's exporters is a
    # list, evaluator_task.py:103-121).
    if core.exporters is None:
        exporter_fns = []
    elif callable(core.exporters):
        exporter_fns = [core.exporters]
    else:
        exporter_fns = list(core.exporters)

    done = _evaluated_steps(core.model_dir)
    final_step = core.train_params.train_steps
    last_metrics: dict = {}
    last_new = time.time()
    awake_time = 0.0
    start_time = time.time()
    nb_eval_steps = 0
    n_try = runtime.n_try if runtime is not None else 0

    def broadcast_health(eval_elapsed: float, n_batches: int, step: int) -> None:
        if runtime is None:
            return
        total = max(time.time() - start_time, 1e-9)
        stats = {
            "awake_time_ratio": f"{awake_time / total:.4f}",
            "eval_step_mean_duration": f"{eval_elapsed / max(n_batches, 1):.4f}",
            "last_training_step": str(step),
            "nb_eval_steps": str(nb_eval_steps),
        }
        for key, value in stats.items():
            event.broadcast(runtime.kv, f"{runtime.task}/{key}", value)

    while True:
        pending = [
            s for s in ckpt_lib.list_checkpoint_steps(core.model_dir) if s not in done
        ]
        for step in pending:
            t0 = time.time()
            try:
                # Host (numpy) restore: the training mesh's sharded save
                # must be readable on the evaluator's single CPU device.
                state = ckpt_lib.restore_checkpoint_host(core.model_dir, step)
            except Exception as exc:  # partially-written ckpt; retry next poll
                _logger.warning("could not restore ckpt-%d yet: %s", step, exc)
                continue

            from tf_yarn_tpu.training import TrainState

            params = state["params"] if isinstance(state, dict) else state.params
            eval_state = TrainState(step=0, params=params, opt_state=())

            # Count actually-consumed eval batches (the input may be
            # shorter than eval_steps) so the health metrics stay honest.
            consumed = {"n": 0}

            def counted_input():
                for batch in eval_input_fn():
                    consumed["n"] += 1
                    yield batch

            # Evaluator runs single-device (CPU): identity globalizer.
            metrics = evaluate(
                eval_step,
                eval_state,
                counted_input,
                lambda b: b,
                core.train_params.eval_steps,
                rng,
            )
            elapsed = time.time() - t0
            awake_time += elapsed
            nb_eval_steps += consumed["n"]
            for exporter in exporter_fns:
                # Post-eval export hooks (reference: eval_spec.exporters
                # run after each evaluation, evaluator_task.py:103-121).
                try:
                    exporter(params, metrics, step)
                except Exception:
                    _logger.exception("exporter failed for ckpt-%d", step)
            last_metrics = metrics
            done.add(step)
            last_new = time.time()
            _mark_evaluated(core.model_dir, step, metrics)
            _logger.info("evaluated ckpt-%d: %s (%.1fs)", step, metrics, elapsed)
            for key, value in metrics.items():
                mlflow.log_metric(f"eval_{key}_{n_try}", value, step=step)
            broadcast_health(elapsed, consumed["n"], step)

        if any(s >= final_step for s in done):
            _logger.info("final checkpoint (step %d) evaluated; stopping", final_step)
            break
        if _training_finished(runtime):
            # Training ended early (input exhausted before train_steps):
            # re-list to catch a final checkpoint written just before the
            # stop event, then finish without the 20-min idle wait.
            remaining = [
                s
                for s in ckpt_lib.list_checkpoint_steps(core.model_dir)
                if s not in done
            ]
            if not remaining:
                _logger.info("training stopped and no pending ckpts; stopping")
                break
        if time.time() - last_new > idle_timeout_secs:
            _logger.info("no new checkpoint for %.0fs; stopping", idle_timeout_secs)
            break
        time.sleep(poll_secs)
    return last_metrics


def _training_finished(runtime: Optional[_bootstrap.TaskRuntime]) -> bool:
    """True when every chief/worker has broadcast its stop event."""
    if runtime is None:
        return False
    primaries = [
        ti for ti in runtime.cluster_tasks if ti.key.type in ("chief", "worker")
    ]
    return bool(primaries) and all(
        runtime.kv.get(f"{ti.to_kv_str()}/{event.STOP}") is not None
        for ti in primaries
    )
