"""Benchmark: flagship training-step throughput on the local accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no numbers (BASELINE.md: "None"), so vs_baseline
compares against the value recorded in BENCH_BASELINE.json when present
(our own previous round), else 1.0.
"""

from __future__ import annotations

import json
import os
import sys
import time


def _log(*args) -> None:
    print(*args, file=sys.stderr, flush=True)


def bench_flagship_train(steps: int = 20, warmup: int = 3):
    import jax
    import numpy as np
    import optax

    from tf_yarn_tpu.models import common
    from tf_yarn_tpu.models.transformer import Transformer, TransformerConfig
    from tf_yarn_tpu.parallel.mesh import MeshSpec, build_mesh, select_devices
    from tf_yarn_tpu.parallel.sharding import tree_shardings, unbox_params
    from tf_yarn_tpu.training import TrainState, build_train_step

    devices = select_devices()
    on_tpu = devices[0].platform == "tpu"
    _log(f"benchmarking on {len(devices)} x {devices[0].device_kind}")

    if on_tpu:
        # remat off: this config's activations fit one chip's HBM, so
        # recompute would only burn MXU cycles.
        config = TransformerConfig(
            vocab_size=32000, d_model=1024, n_layers=8, n_heads=16,
            n_kv_heads=8, d_ff=4096, max_seq_len=2048, remat=False,
        )
        batch_size, seq_len = 8, 1024
    else:  # CPU smoke fallback so the bench always emits a line
        config = TransformerConfig.tiny()
        batch_size, seq_len = 8, 64
        steps, warmup = 5, 1

    spec = MeshSpec.auto(len(devices))
    mesh = build_mesh(spec, devices)
    model = Transformer(config)
    optimizer = optax.adamw(1e-4)
    rng = jax.random.PRNGKey(0)
    tokens = np.random.RandomState(0).randint(
        0, config.vocab_size, (batch_size, seq_len), dtype=np.int32
    )

    with mesh:
        def init_state(rng, tokens):
            variables = model.init(rng, tokens)
            params = unbox_params(variables)
            return TrainState(np.int32(0), params, optimizer.init(params))

        def init_boxed(rng, tokens):
            variables = model.init(rng, tokens)
            return TrainState(np.int32(0), variables, optimizer.init(variables))

        abstract = jax.eval_shape(init_boxed, rng, tokens)
        shardings = tree_shardings(mesh, abstract)
        state = jax.jit(init_state, out_shardings=shardings)(rng, tokens)
        step_fn = jax.jit(
            build_train_step(model, common.lm_loss, optimizer),
            donate_argnums=(0,),
            out_shardings=(shardings, None),
        )
        batch = {"tokens": jax.device_put(tokens)}

        t0 = time.time()
        for _ in range(warmup):
            state, metrics = step_fn(state, batch, rng)
        jax.block_until_ready(state.params)
        _log(f"warmup ({warmup} steps incl. compile): {time.time() - t0:.1f}s")

        t0 = time.time()
        for _ in range(steps):
            state, metrics = step_fn(state, batch, rng)
        jax.block_until_ready(state.params)
        elapsed = time.time() - t0

    samples_per_sec = steps * batch_size / elapsed
    per_chip = samples_per_sec / len(devices)
    _log(f"{steps} steps in {elapsed:.2f}s; loss={float(metrics['loss']):.3f}")
    return {
        "metric": "flagship_train_samples_per_sec_per_chip",
        "value": round(per_chip, 3),
        "unit": f"samples/sec/chip (d_model={config.d_model}, "
        f"layers={config.n_layers}, seq={seq_len}, bf16, "
        f"{'tpu' if on_tpu else 'cpu-fallback'})",
    }


def main() -> None:
    result = bench_flagship_train()
    baseline_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    vs_baseline = 1.0
    if os.path.exists(baseline_path):
        try:
            with open(baseline_path) as fh:
                baseline = json.load(fh)
            if baseline.get("metric") == result["metric"] and baseline.get("value"):
                vs_baseline = round(result["value"] / float(baseline["value"]), 3)
        except (ValueError, OSError):
            pass
    result["vs_baseline"] = vs_baseline
    print(json.dumps(result))


if __name__ == "__main__":
    main()
