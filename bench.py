"""Benchmark: flagship training-step throughput on the local accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "mfu": N, ...}

The reference publishes no numbers (BASELINE.md: "None"), so vs_baseline
compares against the value recorded in BENCH_BASELINE.json when present
(our own previous round), else 1.0. The full per-config suite lives in
benchmarks/run.py.

On TPU the bench A/Bs the kernel knobs (attention_impl=xla|flash,
fused_norms on/off), adds decode (bf16 vs int8 KV cache) and long-context
(S=8192) lines, and writes everything to BENCH_AB.json with measurement
provenance (device, git commit, timestamp). The headline reports the
*best* training variant (the unit string names the winning impl).

When the accelerator is unreachable (a wedged relay can hang device init
past any probe budget), the bench still reports the last committed TPU
measurement from BENCH_AB.json as explicitly-labeled `last_tpu_*` fields
next to the fresh CPU smoke number — honest staleness beats losing the
hardware evidence (round-2 verdict item 1).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.abspath(__file__))
_AB_PATH = os.path.join(_REPO, "BENCH_AB.json")

# The flagship TPU bench config. Module-level so the stale-provenance
# path can tell whether a carried-forward number measured THIS model
# (round-3 verdict weak #6: best-row selection must not silently compare
# different configs across rounds).
_TPU_BASE = dict(
    vocab_size=32000, d_model=1024, n_layers=8, n_heads=16,
    n_kv_heads=8, d_ff=4096, max_seq_len=2048, remat=False,
)
_TPU_BATCH, _TPU_SEQ, _TPU_STEPS = 8, 1024, 20


def _config_hash(cfg: dict) -> str:
    import hashlib

    return hashlib.sha256(
        json.dumps(cfg, sort_keys=True, default=str).encode()
    ).hexdigest()[:12]


def _code_hash() -> str:
    """Fingerprint of the kernel + train-loop source a TPU measurement
    depends on. A carried-forward TPU number can then never silently
    claim currency across a kernel rewrite (round-4 verdict weak #1:
    `last_tpu_config_matches_current` pinned only the model config while
    every pallas call path changed underneath it)."""
    import glob
    import hashlib

    digest = hashlib.sha256()
    paths = sorted(
        glob.glob(os.path.join(_REPO, "tf_yarn_tpu", "ops", "*.py"))
        # The kernel DISPATCH (attention_impl / fused_norms wiring) lives
        # in the model files — a rewrite there changes what a TPU number
        # measures just as surely as a kernel edit.
        + glob.glob(os.path.join(_REPO, "tf_yarn_tpu", "models", "*.py"))
    )
    paths.append(os.path.join(_REPO, "tf_yarn_tpu", "training.py"))
    paths.append(os.path.join(_REPO, "tf_yarn_tpu", "benchmark.py"))
    paths.append(os.path.join(_REPO, "benchmarks", "run.py"))
    for path in paths:
        try:
            with open(path, "rb") as fh:
                digest.update(os.path.basename(path).encode())
                digest.update(fh.read())
        except OSError:
            digest.update(f"missing:{os.path.basename(path)}".encode())
    return digest.hexdigest()[:12]


def _uncommitted_bench_files() -> set:
    """Basenames of BENCH_r*.json not committed to HEAD. Prior rounds'
    files are committed by the end-of-round snapshot; anything untracked
    or modified belongs to the round in flight."""
    try:
        out = subprocess.run(
            ["git", "-C", _REPO, "status", "--porcelain", "--",
             "BENCH_r*.json"],
            capture_output=True, text=True, timeout=10,
        )
        if out.returncode:
            return set()
        return {
            os.path.basename(line[3:].strip())
            for line in out.stdout.splitlines()
            if line.strip()
        }
    except Exception:
        return set()


def _prior_round_cpu_value():
    """(round file, value) of the newest PRIOR round's driver-recorded
    CPU-fallback headline, for drift detection across rounds (round-4
    verdict weak #2: 521.9 -> 456.4 samples/s went unnoticed and
    unexplained).

    Two traps (ADVICE r5 item 1): the current round's own file is
    already on disk on a re-run within a round — comparing against it
    mutes the cross-round signal, so uncommitted files are excluded —
    and lexical glob order silently depends on zero-padded round
    numbers, so candidates sort by the *parsed* round number.
    """
    import glob
    import re

    candidates = []
    for path in glob.glob(os.path.join(_REPO, "BENCH_r*.json")):
        match = re.fullmatch(r"BENCH_r(\d+)\.json", os.path.basename(path))
        if match:
            candidates.append((int(match.group(1)), path))
    current_round = _uncommitted_bench_files()
    for _round_num, path in sorted(candidates, reverse=True):
        if os.path.basename(path) in current_round:
            continue
        try:
            with open(path) as fh:
                parsed = json.load(fh).get("parsed") or {}
        except (OSError, ValueError):
            continue
        if "cpu-fallback" in str(parsed.get("unit", "")) and parsed.get("value"):
            return (os.path.basename(path), float(parsed["value"]))
    return None


def _log(*args) -> None:
    print(*args, file=sys.stderr, flush=True)


def _accel_env() -> dict:
    """TPU_*/JAX_*/XLA_* env for the wedge postmortem."""
    return {
        k: v for k, v in os.environ.items()
        if k.startswith(("TPU_", "JAX_", "XLA_", "LIBTPU", "PJRT_"))
    }


def _accel_holders() -> tuple:
    """(holders, uninspectable): other processes holding accelerator
    device files or the libtpu lockfile — the usual cause of a device-init
    hang that no amount of waiting fixes (an orphan from a SIGKILLed run
    keeps the chip). `uninspectable` counts live pids whose fd tables we
    could not read (another user's process): with any of those, "no
    holder found" proves nothing and remediation must not assume the
    lockfile is stale."""
    holders = []
    uninspectable = 0
    try:
        pids = [p for p in os.listdir("/proc") if p.isdigit()]
    except OSError:
        return holders, 1
    me = os.getpid()
    for pid in pids:
        if int(pid) == me:
            continue
        fd_dir = f"/proc/{pid}/fd"
        try:
            fds = os.listdir(fd_dir)
        except OSError:
            if os.path.isdir(f"/proc/{pid}"):
                uninspectable += 1  # permission-denied, not a raced exit
            continue
        for fd in fds:
            try:
                target = os.readlink(os.path.join(fd_dir, fd))
            except OSError:
                continue
            if ("/dev/accel" in target or "libtpu_lockfile" in target
                    or "/dev/vfio" in target):
                try:
                    with open(f"/proc/{pid}/cmdline", "rb") as fh:
                        cmd = fh.read().replace(b"\0", b" ").decode(
                            errors="replace").strip()[:160]
                except OSError:
                    cmd = "?"
                holders.append({"pid": int(pid), "file": target, "cmd": cmd})
                break
    return holders, uninspectable


def _attempt_unwedge(attempt: int) -> None:
    """Between probes, try the recoverable causes of a hung device init
    instead of only waiting out the budget (round-3 verdict item 3):
    report orphan processes holding the chip, remove a stale
    /tmp/libtpu_lockfile nobody holds, and log the accelerator env once
    for the postmortem."""
    if attempt == 1:
        _log(f"accelerator env: {json.dumps(_accel_env(), sort_keys=True)}")
    holders, uninspectable = _accel_holders()
    if holders:
        # Killing someone else's process is not the bench's call — but
        # naming it turns "relay wedged all round" into an actionable
        # report.
        _log(f"accelerator held by other processes: {json.dumps(holders)}")
        return
    if uninspectable:
        # A pid we couldn't inspect may be the holder: removing the
        # lockfile under a live holder would make two processes contend
        # for the chip. Report and leave it.
        _log(f"{uninspectable} live processes uninspectable; not touching "
             "the lockfile")
        return
    lock = "/tmp/libtpu_lockfile"
    if os.path.exists(lock):
        try:
            os.unlink(lock)
            _log(f"removed stale {lock} (no live holder)")
        except OSError as exc:
            _log(f"could not remove {lock}: {exc}")


def _probe_backend_alive() -> bool:
    """Check device init in a throwaway subprocess, retrying with backoff.

    A wedged TPU relay hangs `jax.devices()` indefinitely — but it is
    also known to *recover*, so a single failed probe must not condemn
    the whole bench to the CPU fallback (round-1 verdict). We keep
    probing until TPU_YARN_BENCH_PROBE_BUDGET_S (default 900s) is spent,
    then degrade.
    """
    if os.environ.get("TPU_YARN_PLATFORM"):
        return True  # explicitly forced; nothing to probe

    budget = float(os.environ.get("TPU_YARN_BENCH_PROBE_BUDGET_S", "900"))
    deadline = time.time() + budget
    attempt, backoff = 0, 30.0
    hard_failures = 0
    while True:
        attempt += 1
        per_try = max(30.0, min(180.0, deadline - time.time()))
        try:
            probe = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=per_try,
                capture_output=True,
            )
            if probe.returncode == 0:
                return True
            # Fast non-zero exits are permanent breakage (jax/libtpu
            # misconfig), not the recoverable wedged-relay hang the budget
            # exists for — don't burn 15 minutes on them.
            hard_failures += 1
            _log(f"probe attempt {attempt}: device init failed "
                 f"(rc={probe.returncode})")
            if hard_failures >= 3:
                _log("3 hard failures: backend is broken, not wedged")
                return False
        except subprocess.TimeoutExpired:
            hard_failures = 0
            _log(f"probe attempt {attempt}: device init hung {per_try:.0f}s")
        _attempt_unwedge(attempt)
        remaining = deadline - time.time()
        if remaining <= 1:
            return False
        wait = min(backoff, remaining)
        _log(f"retrying probe in {wait:.0f}s ({remaining:.0f}s budget left)")
        time.sleep(wait)
        backoff = min(backoff * 2, 240.0)


def _git_head() -> str:
    try:
        return subprocess.run(
            ["git", "-C", _REPO, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
        ).stdout.strip()
    except Exception:
        return ""


def _ab_file_provenance() -> dict:
    """(commit, date) the committed BENCH_AB.json was last touched at —
    the provenance trail for stale reporting when the file predates the
    embedded measured_at/git_commit fields."""
    try:
        out = subprocess.run(
            ["git", "-C", _REPO, "log", "-1", "--format=%h|%cI", "--",
             "BENCH_AB.json"],
            capture_output=True, text=True, timeout=10,
        ).stdout.strip()
        commit, _, date = out.partition("|")
        return {"git_commit": commit, "measured_at": date}
    except Exception:
        return {"git_commit": "", "measured_at": ""}


def _stale_tpu_fields() -> dict:
    """last_tpu_* fields from the committed A/B table, or {}."""
    try:
        with open(_AB_PATH) as fh:
            table = json.load(fh)
    except (OSError, ValueError):
        return {}
    rows = [r for r in table.get("rows", []) if "error" not in r]
    if not rows:
        return {}
    best = max(rows, key=lambda r: r.get("samples_per_sec_per_chip", 0.0))
    provenance = {
        "git_commit": table.get("git_commit"),
        "measured_at": table.get("measured_at"),
    }
    if not provenance["git_commit"]:
        provenance = _ab_file_provenance()
    stale_hash = table.get("config_hash") or (
        _config_hash(table["config"]) if table.get("config") else None
    )
    current_hash = _config_hash(
        {**_TPU_BASE, "batch": _TPU_BATCH, "seq": _TPU_SEQ})
    fields = {
        "last_tpu_value": best["samples_per_sec_per_chip"],
        "last_tpu_mfu": best.get("mfu"),
        "last_tpu_variant": best.get("variant"),
        "last_tpu_device": table.get("device"),
        "last_tpu_commit": provenance["git_commit"],
        "last_tpu_date": provenance["measured_at"],
        # Pin WHAT was measured: a future dim change must be visible,
        # not silently compared across rounds.
        "last_tpu_config_hash": stale_hash,
        "last_tpu_config_matches_current": (
            stale_hash == current_hash if stale_hash else None
        ),
        # Pin the CODE too: a table written before the current kernel /
        # train-loop source (or one with no recorded code hash at all)
        # reports False — the number measured different code.
        "last_tpu_code_hash": table.get("code_hash"),
        "last_tpu_code_matches_current": (
            table.get("code_hash") == _code_hash()
            if table.get("code_hash")
            else False
        ),
    }
    decode = table.get("decode") or {}
    for key in ("decode_tokens_per_sec_bf16", "decode_tokens_per_sec_int8",
                "engine_tokens_per_sec_bf16", "engine_tokens_per_sec_int8",
                "percall_jit_tokens_per_sec_bf16",
                "percall_jit_tokens_per_sec_int8"):
        if key in decode:
            fields[f"last_tpu_{key}"] = decode[key]
    longctx = table.get("long_context") or {}
    if "tokens_per_sec_per_chip" in longctx:
        fields["last_tpu_longctx_tokens_per_sec"] = longctx[
            "tokens_per_sec_per_chip"
        ]
    serve = table.get("serve") or {}
    for policy in ("continuous", "static"):
        row = serve.get(policy) or {}
        if "tokens_per_sec" in row:
            fields[f"last_tpu_serve_{policy}_tokens_per_sec"] = row[
                "tokens_per_sec"
            ]
            fields[f"last_tpu_serve_{policy}_ttft_p95_ms"] = row.get(
                "ttft_p95_ms"
            )
    for layout in ("dense", "paged", "paged_int8"):
        row = (serve.get("layouts") or {}).get(layout) or {}
        if "tokens_per_sec" in row:
            fields[f"last_tpu_serve_{layout}_tokens_per_sec"] = row[
                "tokens_per_sec"
            ]
            fields[f"last_tpu_serve_{layout}_slots_per_gb_hbm"] = row.get(
                "slots_per_gb_hbm"
            )
    for key in ("paged_vs_dense_slots_per_gb",
                "paged_int8_vs_dense_slots_per_gb"):
        if key in serve:
            fields[f"last_tpu_serve_{key}"] = serve[key]
    for row_name, row in ((serve.get("spec") or {}).get("rows") or {}).items():
        if isinstance(row, dict) and "tokens_per_sec" in row:
            fields[f"last_tpu_serve_spec_{row_name}_tokens_per_sec"] = row[
                "tokens_per_sec"
            ]
            fields[
                f"last_tpu_serve_spec_{row_name}_accepted_tokens_per_step"
            ] = row.get("accepted_tokens_per_step")
    tp_ab = serve.get("tp") or {}
    for row_name, row in (tp_ab.get("rows") or {}).items():
        if isinstance(row, dict) and "tokens_per_sec" in row:
            fields[f"last_tpu_serve_tp_{row_name}_tokens_per_sec"] = row[
                "tokens_per_sec"
            ]
            fields[
                f"last_tpu_serve_tp_{row_name}_kv_hbm_bytes_per_device"
            ] = row.get("kv_hbm_bytes_per_device")
    if "kv_per_device_ratio" in tp_ab:
        fields["last_tpu_serve_tp_kv_per_device_ratio"] = tp_ab[
            "kv_per_device_ratio"
        ]
    chunked_ab = serve.get("chunked") or {}
    for row_name, row in (chunked_ab.get("rows") or {}).items():
        if isinstance(row, dict) and "itl_p95_ms" in row:
            fields[f"last_tpu_serve_chunked_{row_name}_itl_p95_ms"] = row[
                "itl_p95_ms"
            ]
            fields[f"last_tpu_serve_chunked_{row_name}_ttft_p95_ms"] = (
                row.get("ttft_p95_ms")
            )
    if "itl_p95_ratio" in chunked_ab:
        fields["last_tpu_serve_chunked_itl_p95_ratio"] = chunked_ab[
            "itl_p95_ratio"
        ]
    overload_ab = serve.get("overload") or {}
    for row_name, row in (overload_ab.get("rows") or {}).items():
        if isinstance(row, dict) and "peak_streams" in row:
            fields[f"last_tpu_serve_overload_{row_name}_peak_streams"] = (
                row["peak_streams"]
            )
            fields[
                f"last_tpu_serve_overload_{row_name}"
                "_interactive_ttft_p95_ms"
            ] = row.get("interactive_ttft_p95_ms")
    for key in ("peak_streams_ratio", "interactive_ttft_p95_ratio"):
        if key in overload_ab:
            fields[f"last_tpu_serve_overload_{key}"] = overload_ab[key]
    disagg_ab = serve.get("disagg") or {}
    for row_name, row in (disagg_ab.get("rows") or {}).items():
        if isinstance(row, dict) and "ttft_p95_ms" in row:
            fields[f"last_tpu_serve_disagg_{row_name}_ttft_p95_ms"] = row[
                "ttft_p95_ms"
            ]
    for key in ("ttft_p95_ratio", "wire_bytes_fp_over_int8"):
        if key in disagg_ab:
            fields[f"last_tpu_serve_disagg_{key}"] = disagg_ab[key]
    if "streams_match_local" in (
        (disagg_ab.get("rows") or {}).get("offloaded") or {}
    ):
        fields["last_tpu_serve_disagg_streams_match_local"] = disagg_ab[
            "rows"
        ]["offloaded"]["streams_match_local"]
    fleet = table.get("fleet") or {}
    for row_name, row in (fleet.get("rows") or {}).items():
        if isinstance(row, dict) and "tokens_per_sec" in row:
            fields[f"last_tpu_fleet_{row_name}_tokens_per_sec"] = row[
                "tokens_per_sec"
            ]
            fields[f"last_tpu_fleet_{row_name}_ttft_p95_ms"] = row.get(
                "ttft_p95_ms"
            )
            # Observability-plane numbers (PR 18): the scrape-merged
            # fleet TTFT p95 and the monitor's per-cycle scrape cost.
            if "fleet_ttft_p95_ms" in row:
                fields[
                    f"last_tpu_fleet_{row_name}_merged_ttft_p95_ms"
                ] = row["fleet_ttft_p95_ms"]
            if "monitor_scrape_wall_ms" in row:
                fields[
                    f"last_tpu_fleet_{row_name}_monitor_scrape_wall_ms"
                ] = row["monitor_scrape_wall_ms"]
    for key, value in fleet.items():
        if str(key).startswith("scaling_"):
            fields[f"last_tpu_fleet_{key}"] = value
    # Elastic A/B (autoscaler vs static fleet): violation rates per
    # arm, the delta, and the bit-identity flag. CPU reruns never
    # overwrite these — the TPU row is the capacity claim; a CPU rig's
    # rows are scheduling evidence only (the section's note says so).
    autoscale_ab = fleet.get("autoscale") or {}
    for row_name, row in (autoscale_ab.get("rows") or {}).items():
        if isinstance(row, dict) and "slo_violation_rate" in row:
            fields[
                f"last_tpu_fleet_autoscale_{row_name}_slo_violation_rate"
            ] = row["slo_violation_rate"]
            fields[f"last_tpu_fleet_autoscale_{row_name}_ttft_p95_ms"] = (
                row.get("ttft_p95_ms")
            )
    for key in ("violation_delta", "streams_match"):
        if key in autoscale_ab:
            fields[f"last_tpu_fleet_autoscale_{key}"] = autoscale_ab[key]
    rank = table.get("rank") or {}
    for row_name, row in (rank.get("rows") or {}).items():
        if isinstance(row, dict) and "requests_per_sec" in row:
            fields[f"last_tpu_rank_{row_name}_requests_per_sec"] = row[
                "requests_per_sec"
            ]
            fields[f"last_tpu_rank_{row_name}_latency_p95_ms"] = row.get(
                "latency_p95_ms"
            )
            fields[f"last_tpu_rank_{row_name}_rows_per_tick"] = row.get(
                "rows_per_tick"
            )
    return fields


def _write_ab(table: dict) -> None:
    try:
        with open(_AB_PATH, "w") as fh:
            json.dump(table, fh, indent=1)
        _log(f"A/B table -> {_AB_PATH}")
    except OSError as exc:
        _log(f"could not write A/B table: {exc}")


def _load_bench_suite():
    """benchmarks/run.py as a module (no package __init__ there)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "tpu_yarn_bench_suite", os.path.join(_REPO, "benchmarks", "run.py")
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def _run_variant(config, batch_size: int, seq_len: int, steps: int,
                 devices):
    import numpy as np
    import optax

    from tf_yarn_tpu.benchmark import measure_throughput
    from tf_yarn_tpu.models import common
    from tf_yarn_tpu.models.transformer import Transformer

    tokens = np.random.RandomState(0).randint(
        0, config.vocab_size, (batch_size, seq_len), dtype=np.int32
    )
    return measure_throughput(
        Transformer(config),
        common.lm_loss,
        optax.adamw(1e-4),
        {"tokens": tokens},
        steps=steps,
        devices=devices,
    )


def bench_flagship_train():
    if not _probe_backend_alive():
        _log("default backend unreachable (hung device init, budget spent); "
             "forcing CPU")
        os.environ["TPU_YARN_PLATFORM"] = "cpu"

    from tf_yarn_tpu.models.transformer import TransformerConfig
    from tf_yarn_tpu.parallel.mesh import select_devices

    devices = select_devices()
    on_tpu = devices[0].platform == "tpu"
    _log(f"benchmarking on {len(devices)} x {devices[0].device_kind}")

    if on_tpu:
        # remat off: this config's activations fit one chip's HBM, so
        # recompute would only burn MXU cycles.
        base = dict(_TPU_BASE)
        batch_size, seq_len, steps = _TPU_BATCH, _TPU_SEQ, _TPU_STEPS
        # Axes: layer-scan on/off (unrolling lets XLA fuse across layer
        # boundaries — measured ~+25% on v5e), attention xla/flash, fused
        # pallas norms on/off.
        variants = [
            ("xla", dict(attention_impl="xla", fused_norms=False)),
            ("xla+fused_norms", dict(attention_impl="xla", fused_norms=True)),
            ("xla+fused+unroll", dict(attention_impl="xla", fused_norms=True,
                                      scan_layers=False)),
            # fused norms with the recompute backward (round-4 behavior)
            # vs the round-5 dx kernels — the rmsnorm-bwd A/B
            # (TPU_YARN_NORM_KERNEL_BWD env seam, docs/Performance.md).
            ("flash+fused+unroll+bwd_recompute",
             dict(attention_impl="flash", fused_norms=True,
                  scan_layers=False, _norm_kernel_bwd=False)),
            ("flash+fused+unroll", dict(attention_impl="flash",
                                        fused_norms=True, scan_layers=False)),
        ]
    else:  # CPU smoke fallback so the bench always emits a line
        base = None
        batch_size, seq_len, steps = 8, 64, 5
        variants = [("xla", None)]

    table = []
    model_desc = None
    # The CPU smoke number is a 5-step tiny-model run with ~±7% run-to-
    # run noise (measured round 5); the median of 3 reps keeps the cross-
    # round drift signal meaningful. TPU runs are long enough already.
    reps = 1 if on_tpu else 3
    for name, overrides in variants:
        overrides = dict(overrides) if overrides is not None else None
        norm_bwd = (overrides.pop("_norm_kernel_bwd", True)
                    if overrides is not None else True)
        config = (TransformerConfig(**{**base, **overrides})
                  if overrides is not None else TransformerConfig.tiny())
        model_desc = f"d_model={config.d_model}, layers={config.n_layers}"
        from tf_yarn_tpu.benchmark import kernel_bwd_env

        try:
            with kernel_bwd_env(norm_bwd):
                runs = sorted(
                    (_run_variant(config, batch_size, seq_len, steps, devices)
                     for _ in range(reps)),
                    key=lambda s: s["samples_per_sec_per_chip"],
                )
            stats = runs[len(runs) // 2]
        except Exception as exc:  # a broken kernel must not kill the bench
            _log(f"variant {name}: FAILED: {type(exc).__name__}: {exc}")
            table.append({"variant": name, "error": f"{exc}"})
            continue
        row = {
            "variant": name,
            "samples_per_sec_per_chip": round(
                stats["samples_per_sec_per_chip"], 3),
            "step_time_ms": round(stats["step_time_ms"], 2),
            "mfu": round(stats["mfu"], 4) if "mfu" in stats else None,
            "final_loss": round(stats["final_loss"], 4),
        }
        table.append(row)
        _log(f"variant {name}: {row['samples_per_sec_per_chip']} samples/s/chip, "
             f"step {row['step_time_ms']}ms, mfu={row['mfu']}")

    ok_rows = [r for r in table if "error" not in r]
    if not ok_rows:
        # Even a fully-failed sweep must emit the one JSON line.
        result = {
            "metric": "flagship_train_samples_per_sec_per_chip",
            "value": 0.0,
            "unit": "samples/sec/chip (all variants failed: "
            + "; ".join(str(r.get("error", ""))[:80] for r in table) + ")",
        }
        result.update(_stale_tpu_fields())
        if not on_tpu:
            # The serve layout A/B does not ride the train mesh — it can
            # still land its memory-accounting evidence.
            _record_cpu_serve_ab(result)
        return result, None
    best = max(ok_rows, key=lambda r: r["samples_per_sec_per_chip"])

    result = {
        "metric": "flagship_train_samples_per_sec_per_chip",
        "value": best["samples_per_sec_per_chip"],
        "unit": f"samples/sec/chip ({model_desc}, seq={seq_len}, "
        f"bf16, {'tpu, ' + best['variant'] if on_tpu else 'cpu-fallback'})",
    }
    if best.get("mfu") is not None:
        result["mfu"] = best["mfu"]

    if not on_tpu:
        # Cross-round drift check on the CPU-fallback headline: the same
        # tiny config should not silently lose throughput round over
        # round (round-4 verdict weak #2).
        prior = _prior_round_cpu_value()
        if prior:
            prior_file, prior_value = prior
            drift_pct = round(100.0 * (result["value"] / prior_value - 1), 1)
            result["cpu_prev_value"] = prior_value
            result["cpu_prev_round_file"] = prior_file
            result["cpu_drift_pct"] = drift_pct
            if abs(drift_pct) > 5.0:
                _log(f"WARNING: cpu-fallback drift {drift_pct:+.1f}% vs "
                     f"{prior_file} ({prior_value}); >5% on the same config "
                     "— investigate before trusting cross-round comparisons")
        # A wedged relay must not erase the hardware evidence: surface the
        # committed TPU measurement with provenance, clearly staleness-
        # labeled, next to the fresh CPU smoke number.
        stale = _stale_tpu_fields()
        if stale:
            _log("attaching last-known TPU measurement "
                 f"({stale.get('last_tpu_device')}, commit "
                 f"{stale.get('last_tpu_commit')}, {stale.get('last_tpu_date')})")
            result.update(stale)
        _record_cpu_serve_ab(result)
        return result, None

    # --- TPU: persist the A/B table incrementally (flagship first, so a
    # timeout mid-extras still leaves it recorded), then fold in decode
    # and long-context — the driver artifact carries all three surfaces.
    # Previous decode/long-context sections are carried forward with a
    # staleness label until their fresh run succeeds: a failed extra must
    # not erase the last hardware evidence for that surface.
    try:
        with open(_AB_PATH) as fh:
            previous = json.load(fh)
    except (OSError, ValueError):
        previous = {}
    ab = {
        "config": {**base, "batch": batch_size, "seq": seq_len},
        "config_hash": _config_hash({**base, "batch": batch_size,
                                     "seq": seq_len}),
        "code_hash": _code_hash(),
        "device": devices[0].device_kind,
        "git_commit": _git_head(),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "rows": table,
    }
    for section in ("decode", "long_context", "serve", "fleet", "rank",
                    "bert_base", "resnet50", "vit_base"):
        if previous.get(section):
            ab[section] = {
                **previous[section],
                # Keep the ORIGINAL measurement commit across repeated
                # carry-forwards — previous.git_commit is only right the
                # first time the section goes stale.
                "stale_from_commit": previous[section].get("stale_from_commit")
                or previous.get("git_commit")
                or _ab_file_provenance()["git_commit"],
            }
    _write_ab(ab)

    suite = None
    try:
        suite = _load_bench_suite()
    except Exception as exc:
        _log(f"could not load benchmarks/run.py: {exc}")
    if suite is not None:
        try:
            decode = suite.bench_decode(tpu=True)
            ab["decode"] = decode
            _write_ab(ab)
            result["decode_tokens_per_sec_bf16"] = decode[
                "decode_tokens_per_sec_bf16"]
            result["decode_tokens_per_sec_int8"] = decode[
                "decode_tokens_per_sec_int8"]
            # Serving-path A/B (DecodeEngine vs per-call jit), when the
            # suite produced it.
            for key in ("engine_tokens_per_sec_bf16",
                        "engine_tokens_per_sec_int8",
                        "percall_jit_tokens_per_sec_bf16",
                        "percall_jit_tokens_per_sec_int8"):
                if key in decode:
                    result[key] = decode[key]
            _log(f"decode: {decode}")
        except Exception as exc:
            _log(f"decode bench FAILED: {type(exc).__name__}: {exc}")
        try:
            serve = suite.bench_serve(tpu=True, tp=True, chunked=True,
                                      overload=True, disagg=True)
            ab["serve"] = serve
            _write_ab(ab)
            # Online-serving headline pair: continuous-batching
            # throughput + tail TTFT, with the static-batching baseline
            # alongside (same engine, same trace — policy-only delta).
            for policy in ("continuous", "static"):
                result[f"serve_{policy}_tokens_per_sec"] = (
                    serve[policy]["tokens_per_sec"]
                )
                result[f"serve_{policy}_ttft_p95_ms"] = (
                    serve[policy]["ttft_p95_ms"]
                )
            # KV-layout A/B: slots-per-GB-HBM is the concurrency-per-
            # chip lever paged/int8 exist for (same trace, same slots).
            for layout in ("dense", "paged", "paged_int8"):
                row = (serve.get("layouts") or {}).get(layout) or {}
                if "tokens_per_sec" in row:
                    result[f"serve_{layout}_tokens_per_sec"] = row[
                        "tokens_per_sec"
                    ]
                    result[f"serve_{layout}_slots_per_gb_hbm"] = row.get(
                        "slots_per_gb_hbm"
                    )
            for key in ("paged_vs_dense_slots_per_gb",
                        "paged_int8_vs_dense_slots_per_gb"):
                if key in serve:
                    result[f"serve_{key}"] = serve[key]
            # Speculative decoding A/B: exact vs k ∈ {2, 4} on the
            # repeated-structure trace — tokens/s and accepted-tokens
            # per step are the per-token latency lever's evidence.
            for row_name, row in (
                (serve.get("spec") or {}).get("rows") or {}
            ).items():
                if isinstance(row, dict) and "tokens_per_sec" in row:
                    result[f"serve_spec_{row_name}_tokens_per_sec"] = row[
                        "tokens_per_sec"
                    ]
                    result[
                        f"serve_spec_{row_name}_accepted_tokens_per_step"
                    ] = row.get("accepted_tokens_per_step")
            # Tensor-parallel A/B: tokens/s per tp degree plus the
            # per-device KV residency ratio (the capacity-per-chip
            # claim; on a 1-chip rig the section records its skip note).
            tp_ab = serve.get("tp") or {}
            for row_name, row in (tp_ab.get("rows") or {}).items():
                if isinstance(row, dict) and "tokens_per_sec" in row:
                    result[f"serve_tp_{row_name}_tokens_per_sec"] = row[
                        "tokens_per_sec"
                    ]
                    result[
                        f"serve_tp_{row_name}_kv_hbm_bytes_per_device"
                    ] = row.get("kv_hbm_bytes_per_device")
            if "kv_per_device_ratio" in tp_ab:
                result["serve_tp_kv_per_device_ratio"] = tp_ab[
                    "kv_per_device_ratio"
                ]
            # Chunked-prefill A/B: blocking vs chunked admission on the
            # bimodal trace — inter-token-latency p95 is the no-stall
            # claim (TTFT p95 rides along), streams must match.
            chunked_ab = serve.get("chunked") or {}
            for row_name, row in (chunked_ab.get("rows") or {}).items():
                if isinstance(row, dict) and "itl_p95_ms" in row:
                    result[f"serve_chunked_{row_name}_itl_p95_ms"] = row[
                        "itl_p95_ms"
                    ]
                    result[f"serve_chunked_{row_name}_ttft_p95_ms"] = (
                        row.get("ttft_p95_ms")
                    )
            if "itl_p95_ratio" in chunked_ab:
                result["serve_chunked_itl_p95_ratio"] = chunked_ab[
                    "itl_p95_ratio"
                ]
            # KV-oversubscription A/B: hold-until-free vs suspend-to-
            # host on the overload trace — peak streams is the capacity
            # claim, interactive TTFT p95 the SLO it must not cost,
            # streams_match_hold the bit-identity evidence.
            overload_ab = serve.get("overload") or {}
            for row_name, row in (overload_ab.get("rows") or {}).items():
                if isinstance(row, dict) and "peak_streams" in row:
                    result[f"serve_overload_{row_name}_peak_streams"] = (
                        row["peak_streams"]
                    )
                    result[
                        f"serve_overload_{row_name}_interactive_ttft_p95_ms"
                    ] = row.get("interactive_ttft_p95_ms")
            for key in ("peak_streams_ratio", "interactive_ttft_p95_ratio"):
                if key in overload_ab:
                    result[f"serve_overload_{key}"] = overload_ab[key]
            suspend_row = (overload_ab.get("rows") or {}).get(
                "suspend") or {}
            for key in ("suspends", "resumes", "streams_match_hold"):
                if key in suspend_row:
                    result[f"serve_overload_{key}"] = suspend_row[key]
            # Disaggregated-prefill A/B: offloaded vs local TTFT p95 on
            # the bimodal trace through a real prefill replica over
            # HTTP; streams_match_local is the bit-identity evidence
            # and the fp-vs-int8 ratio the wire saving.
            disagg_ab = serve.get("disagg") or {}
            for row_name, row in (disagg_ab.get("rows") or {}).items():
                if isinstance(row, dict) and "ttft_p95_ms" in row:
                    result[f"serve_disagg_{row_name}_ttft_p95_ms"] = row[
                        "ttft_p95_ms"
                    ]
            for key in ("ttft_p95_ratio", "wire_bytes_fp_over_int8"):
                if key in disagg_ab:
                    result[f"serve_disagg_{key}"] = disagg_ab[key]
            offloaded_row = (disagg_ab.get("rows") or {}).get(
                "offloaded") or {}
            for key in ("streams_match_local", "ships", "shipped_blocks"):
                if key in offloaded_row:
                    result[f"serve_disagg_{key}"] = offloaded_row[key]
            _log(f"serve: {serve}")
        except Exception as exc:
            _log(f"serve bench FAILED: {type(exc).__name__}: {exc}")
        try:
            fleet = suite.bench_fleet(tpu=True)
            ab["fleet"] = fleet
            _write_ab(ab)
            # Fleet scale-out headline: aggregate tokens/s + tail TTFT
            # through the router per replica count, plus the scaling
            # ratios vs one replica (ROADMAP item 1's named bench).
            for row_name, row in (fleet.get("rows") or {}).items():
                if isinstance(row, dict) and "tokens_per_sec" in row:
                    result[f"fleet_{row_name}_tokens_per_sec"] = row[
                        "tokens_per_sec"
                    ]
                    result[f"fleet_{row_name}_ttft_p95_ms"] = row.get(
                        "ttft_p95_ms"
                    )
            for key, value in fleet.items():
                if str(key).startswith("scaling_"):
                    result[f"fleet_{key}"] = value
            _log(f"fleet: {fleet}")
        except Exception as exc:
            _log(f"fleet bench FAILED: {type(exc).__name__}: {exc}")
        try:
            # Elastic A/B (ROADMAP item 1's autoscaler): static fleet
            # vs autoscaled fleet under the same seeded rate-step trace
            # with one injected preemption + relaunch. Headline: the
            # SLO-violation delta and the bit-identity flag.
            fleet_as = suite.bench_fleet(tpu=True, autoscale=True)
            ab.setdefault("fleet", {})["autoscale"] = fleet_as
            _write_ab(ab)
            for row_name, row in (fleet_as.get("rows") or {}).items():
                if isinstance(row, dict) and "slo_violation_rate" in row:
                    result[
                        f"fleet_autoscale_{row_name}_slo_violation_rate"
                    ] = row["slo_violation_rate"]
                    result[f"fleet_autoscale_{row_name}_ttft_p95_ms"] = (
                        row.get("ttft_p95_ms")
                    )
            auto_row = (fleet_as.get("rows") or {}).get("autoscaled") or {}
            for key in ("scale_events", "warm_start_pulls", "warm_starts",
                        "warm_start_blocks"):
                if key in auto_row:
                    result[f"fleet_autoscale_{key}"] = auto_row[key]
            for key in ("violation_delta", "streams_match"):
                if key in fleet_as:
                    result[f"fleet_autoscale_{key}"] = fleet_as[key]
            _log(f"fleet autoscale: {fleet_as}")
        except Exception as exc:
            _log(f"fleet autoscale bench FAILED: "
                 f"{type(exc).__name__}: {exc}")
        try:
            rank = suite.bench_rank(tpu=True)
            ab["rank"] = rank
            _write_ab(ab)
            # Ranking micro-batch headline: requests/s + tail latency
            # per max_wait_ms row — the fill-or-timeout policy trade
            # (docs/Ranking.md) measured on the Criteo-shape DLRM.
            for row_name, row in (rank.get("rows") or {}).items():
                if isinstance(row, dict) and "requests_per_sec" in row:
                    result[f"rank_{row_name}_requests_per_sec"] = row[
                        "requests_per_sec"
                    ]
                    result[f"rank_{row_name}_latency_p95_ms"] = row.get(
                        "latency_p95_ms"
                    )
            _log(f"rank: {rank}")
        except Exception as exc:
            _log(f"rank bench FAILED: {type(exc).__name__}: {exc}")
        try:
            longctx = suite.bench_long_context(tpu=True)
            # Fresh measurement replaces any carried-forward stale section.
            ab["long_context"] = {
                key: longctx[key]
                for key in ("tokens_per_sec_per_chip", "step_time_ms", "mfu",
                            "variants", "attn_microbench")
                if key in longctx
            }
            _write_ab(ab)
            result["longctx_tokens_per_sec"] = longctx["tokens_per_sec_per_chip"]
            if "mfu" in longctx:
                result["longctx_mfu"] = longctx["mfu"]
            _log(f"long_context: {ab['long_context']}")
        except Exception as exc:
            _log(f"long-context bench FAILED: {type(exc).__name__}: {exc}")
    # The full model-family A/B matrices run AFTER the headline JSON
    # line prints (main) — a driver timeout mid-matrix must never cost
    # the round its headline record.
    return result, (suite, ab)


def _record_cpu_serve_ab(result: dict) -> None:
    """The serving KV-layout A/B (dense vs paged vs paged+int8
    slots-per-GB-HBM under one Poisson trace) is tiny-model-cheap, so it
    runs even on the CPU rig: the memory-accounting ratios are layout
    properties, not device speed, and a wedged relay must not leave the
    paged-KV evidence unrecorded. Written to BENCH_AB.json as an
    explicitly CPU-labeled `serve_cpu` section (the TPU `serve` section
    keeps its own provenance), plus `serve_cpu_*` fields on the headline
    line."""
    try:
        suite = _load_bench_suite()
        serve = suite.bench_serve(tpu=False, tp=True, chunked=True,
                                  overload=True, disagg=True)
    except Exception as exc:  # the bench headline must still print
        _log(f"cpu serve bench FAILED: {type(exc).__name__}: {exc}")
        return
    for key in ("paged_vs_dense_slots_per_gb",
                "paged_int8_vs_dense_slots_per_gb"):
        if key in serve:
            result[f"serve_cpu_{key}"] = serve[key]
    layouts = serve.get("layouts") or {}
    for layout in ("dense", "paged", "paged_int8"):
        row = layouts.get(layout) or {}
        if "slots_per_gb_hbm" in row:
            result[f"serve_cpu_{layout}_slots_per_gb_hbm"] = row[
                "slots_per_gb_hbm"
            ]
            result[f"serve_cpu_{layout}_tokens_per_sec"] = row.get(
                "tokens_per_sec"
            )
    # Speculative A/B evidence (accepted-tokens/step is a scheduling
    # property, not device speed — worth recording even CPU-labeled).
    for row_name, row in ((serve.get("spec") or {}).get("rows") or {}).items():
        if isinstance(row, dict) and "tokens_per_sec" in row:
            result[f"serve_cpu_spec_{row_name}_tokens_per_sec"] = row[
                "tokens_per_sec"
            ]
            result[
                f"serve_cpu_spec_{row_name}_accepted_tokens_per_step"
            ] = row.get("accepted_tokens_per_step")
    # Tensor-parallel accounting (per-device KV is a placement
    # property, not device speed — the CPU rig's evidence is real; its
    # tokens/s ratio is NOT, and the section's note says so).
    tp_ab = serve.get("tp") or {}
    for row_name, row in (tp_ab.get("rows") or {}).items():
        if isinstance(row, dict) and "tokens_per_sec" in row:
            result[
                f"serve_cpu_tp_{row_name}_kv_hbm_bytes_per_device"
            ] = row.get("kv_hbm_bytes_per_device")
    if "kv_per_device_ratio" in tp_ab:
        result["serve_cpu_tp_kv_per_device_ratio"] = tp_ab[
            "kv_per_device_ratio"
        ]
    # Chunked-prefill A/B: the bit-identity flag is a scheduling
    # property and holds anywhere; the ITL ratio is device-shaped (the
    # section's note explains why the CPU number is not the claim).
    chunked_ab = serve.get("chunked") or {}
    for row_name, row in (chunked_ab.get("rows") or {}).items():
        if isinstance(row, dict) and "itl_p95_ms" in row:
            result[f"serve_cpu_chunked_{row_name}_itl_p95_ms"] = row[
                "itl_p95_ms"
            ]
    if "itl_p95_ratio" in chunked_ab:
        result["serve_cpu_chunked_itl_p95_ratio"] = chunked_ab[
            "itl_p95_ratio"
        ]
    if "streams_match_blocking" in (
        (chunked_ab.get("rows") or {}).get("chunked") or {}
    ):
        result["serve_cpu_chunked_streams_match_blocking"] = chunked_ab[
            "rows"
        ]["chunked"]["streams_match_blocking"]
    # KV-oversubscription A/B: peak-streams ratio and the bit-identity
    # flag are scheduling properties and hold anywhere; the CPU rig's
    # TTFT/goodput numbers are device-shaped and are NOT recorded as
    # speed evidence (the section's note says so).
    overload_ab = serve.get("overload") or {}
    for row_name, row in (overload_ab.get("rows") or {}).items():
        if isinstance(row, dict) and "peak_streams" in row:
            result[f"serve_cpu_overload_{row_name}_peak_streams"] = row[
                "peak_streams"
            ]
    if "peak_streams_ratio" in overload_ab:
        result["serve_cpu_overload_peak_streams_ratio"] = overload_ab[
            "peak_streams_ratio"
        ]
    suspend_row = (overload_ab.get("rows") or {}).get("suspend") or {}
    for key in ("suspends", "resumes", "streams_match_hold"):
        if key in suspend_row:
            result[f"serve_cpu_overload_{key}"] = suspend_row[key]
    # Disaggregated-prefill A/B: the bit-identity flag and the
    # fp-vs-int8 wire ratio are scheduling/format properties and hold
    # anywhere; the CPU rig's TTFT ratio is device-shaped and is NOT
    # recorded as speed evidence (the section's note says so).
    disagg_ab = serve.get("disagg") or {}
    offloaded_row = (disagg_ab.get("rows") or {}).get("offloaded") or {}
    for key in ("streams_match_local", "ships", "shipped_blocks"):
        if key in offloaded_row:
            result[f"serve_cpu_disagg_{key}"] = offloaded_row[key]
    if "wire_bytes_fp_over_int8" in disagg_ab:
        result["serve_cpu_disagg_wire_bytes_fp_over_int8"] = disagg_ab[
            "wire_bytes_fp_over_int8"
        ]
    try:
        with open(_AB_PATH) as fh:
            table = json.load(fh)
    except (OSError, ValueError):
        table = {}
    table["serve_cpu"] = {
        **serve,
        "device": "cpu",
        "git_commit": _git_head(),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
    }
    _write_ab(table)
    _log(f"cpu serve layout A/B: {serve.get('layouts')}")


def _record_analysis_seconds(result: dict) -> None:
    """Per-engine wall seconds for the four-engine static checker
    (ast/jaxpr/hlo/concurrency over tf_yarn_tpu/), folded into the
    headline line as `analysis_*_s` tracked fields. The checker is a
    tier-1 gate, so its budget drifting up is a regression this line
    makes visible round over round. Device-independent (tiny traced
    shapes, pure-Python lockset scenarios), so it runs on every rig.
    TPU_YARN_BENCH_SKIP_ANALYSIS=1 opts out for a quick run."""
    if os.environ.get("TPU_YARN_BENCH_SKIP_ANALYSIS") == "1":
        return
    try:
        suite = _load_bench_suite()
        stats = suite.bench_analysis(tpu=False)
    except Exception as exc:  # the bench headline must still print
        _log(f"analysis bench FAILED: {type(exc).__name__}: {exc}")
        return
    for key in ("total_s", "ast_s", "jaxpr_s", "hlo_s", "concurrency_s"):
        if key in stats:
            result[f"analysis_{key}"] = round(float(stats[key]), 4)
    if "exit_code" in stats:
        result["analysis_exit_code"] = stats["exit_code"]
    if "error" in stats:
        result["analysis_error"] = stats["error"]
    _log(f"analysis engine seconds: {stats}")


def _run_family_blitz(suite, ab) -> None:
    """The model-family A/B matrices (bert fused-LN fwd/bwd, resnet
    stem/batch, ViT fused-LN): a wedged relay has starved every round of
    these (VERDICT r4 item 1) — capture them in the SAME live-chip
    window as the flagship, incrementally persisted to BENCH_AB.json so
    a timeout mid-matrix keeps the earlier sections.
    TPU_YARN_BENCH_SKIP_FAMILIES=1 opts out for a quick run."""
    if suite is None or os.environ.get("TPU_YARN_BENCH_SKIP_FAMILIES") == "1":
        return
    for section in ("bert_base", "resnet50", "vit_base"):
        try:
            bench_fn = getattr(suite, f"bench_{section}")
            stats = bench_fn(tpu=True)
            ab[section] = {
                key: stats[key]
                for key in ("samples_per_sec_per_chip",
                            "step_time_ms", "mfu", "variants")
                if key in stats
            }
            _write_ab(ab)
            _log(f"{section}: {ab[section]}")
        except Exception as exc:
            _log(f"{section} bench FAILED: {type(exc).__name__}: {exc}")


def main() -> None:
    result, pending_blitz = bench_flagship_train()
    baseline_path = os.path.join(_REPO, "BENCH_BASELINE.json")
    vs_baseline = 1.0
    if os.path.exists(baseline_path):
        try:
            with open(baseline_path) as fh:
                baseline = json.load(fh)
            if baseline.get("metric") == result["metric"] and baseline.get("value"):
                vs_baseline = round(result["value"] / float(baseline["value"]), 3)
        except (ValueError, OSError):
            pass
    result["vs_baseline"] = vs_baseline
    _record_analysis_seconds(result)
    print(json.dumps(result))
    sys.stdout.flush()
    # Post-headline capture: the family matrices only ever ADD to
    # BENCH_AB.json; the one-line stdout contract above is already met,
    # and nothing here may turn the exit status red.
    if pending_blitz is not None:
        try:
            _run_family_blitz(*pending_blitz)
        except Exception as exc:
            _log(f"family blitz FAILED: {type(exc).__name__}: {exc}")


if __name__ == "__main__":
    main()
