"""Benchmark: flagship training-step throughput on the local accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no numbers (BASELINE.md: "None"), so vs_baseline
compares against the value recorded in BENCH_BASELINE.json when present
(our own previous round), else 1.0. The full per-config suite lives in
benchmarks/run.py.
"""

from __future__ import annotations

import json
import os
import sys


def _log(*args) -> None:
    print(*args, file=sys.stderr, flush=True)


def _probe_backend_alive(timeout_secs: float = 180.0) -> bool:
    """Check device init in a throwaway subprocess. A wedged TPU relay
    hangs `jax.devices()` indefinitely; benching must degrade to the CPU
    fallback line rather than hang the caller."""
    import subprocess

    if os.environ.get("TPU_YARN_PLATFORM"):
        return True  # explicitly forced; nothing to probe
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_secs,
            capture_output=True,
        )
        return probe.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def bench_flagship_train():
    if not _probe_backend_alive():
        _log("default backend unreachable (hung device init); forcing CPU")
        os.environ["TPU_YARN_PLATFORM"] = "cpu"

    import numpy as np

    from tf_yarn_tpu.benchmark import measure_throughput
    from tf_yarn_tpu.models import common
    from tf_yarn_tpu.models.transformer import Transformer, TransformerConfig
    from tf_yarn_tpu.parallel.mesh import select_devices

    import optax

    devices = select_devices()
    on_tpu = devices[0].platform == "tpu"
    _log(f"benchmarking on {len(devices)} x {devices[0].device_kind}")

    if on_tpu:
        # remat off: this config's activations fit one chip's HBM, so
        # recompute would only burn MXU cycles.
        config = TransformerConfig(
            vocab_size=32000, d_model=1024, n_layers=8, n_heads=16,
            n_kv_heads=8, d_ff=4096, max_seq_len=2048, remat=False,
        )
        batch_size, seq_len, steps, warmup = 8, 1024, 20, 3
    else:  # CPU smoke fallback so the bench always emits a line
        config = TransformerConfig.tiny()
        batch_size, seq_len, steps, warmup = 8, 64, 5, 1

    model = Transformer(config)
    tokens = np.random.RandomState(0).randint(
        0, config.vocab_size, (batch_size, seq_len), dtype=np.int32
    )
    stats = measure_throughput(
        model,
        common.lm_loss,
        optax.adamw(1e-4),
        {"tokens": tokens},
        steps=steps,
        warmup=warmup,
        devices=devices,
    )
    _log(
        f"compile+warmup {stats['compile_plus_warmup_s']:.1f}s; "
        f"step {stats['step_time_ms']:.1f}ms; loss={stats['final_loss']:.3f}"
    )
    return {
        "metric": "flagship_train_samples_per_sec_per_chip",
        "value": round(stats["samples_per_sec_per_chip"], 3),
        "unit": f"samples/sec/chip (d_model={config.d_model}, "
        f"layers={config.n_layers}, seq={seq_len}, bf16, "
        f"{'tpu' if on_tpu else 'cpu-fallback'})",
    }


def main() -> None:
    result = bench_flagship_train()
    baseline_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    vs_baseline = 1.0
    if os.path.exists(baseline_path):
        try:
            with open(baseline_path) as fh:
                baseline = json.load(fh)
            if baseline.get("metric") == result["metric"] and baseline.get("value"):
                vs_baseline = round(result["value"] / float(baseline["value"]), 3)
        except (ValueError, OSError):
            pass
    result["vs_baseline"] = vs_baseline
    print(json.dumps(result))


if __name__ == "__main__":
    main()
