"""Benchmark: flagship training-step throughput on the local accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "mfu": N}

The reference publishes no numbers (BASELINE.md: "None"), so vs_baseline
compares against the value recorded in BENCH_BASELINE.json when present
(our own previous round), else 1.0. The full per-config suite lives in
benchmarks/run.py.

On TPU the bench also A/Bs the kernel knobs (attention_impl=xla|flash,
fused_norms on/off), writes the table to BENCH_AB.json, and reports the
*best* variant as the headline (the unit string names the winning impl).
"""

from __future__ import annotations

import json
import os
import sys
import time


def _log(*args) -> None:
    print(*args, file=sys.stderr, flush=True)


def _probe_backend_alive() -> bool:
    """Check device init in a throwaway subprocess, retrying with backoff.

    A wedged TPU relay hangs `jax.devices()` indefinitely — but it is
    also known to *recover*, so a single failed probe must not condemn
    the whole bench to the CPU fallback (round-1 verdict). We keep
    probing until TPU_YARN_BENCH_PROBE_BUDGET_S (default 900s) is spent,
    then degrade.
    """
    import subprocess

    if os.environ.get("TPU_YARN_PLATFORM"):
        return True  # explicitly forced; nothing to probe

    budget = float(os.environ.get("TPU_YARN_BENCH_PROBE_BUDGET_S", "900"))
    deadline = time.time() + budget
    attempt, backoff = 0, 30.0
    hard_failures = 0
    while True:
        attempt += 1
        per_try = max(30.0, min(180.0, deadline - time.time()))
        try:
            probe = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=per_try,
                capture_output=True,
            )
            if probe.returncode == 0:
                return True
            # Fast non-zero exits are permanent breakage (jax/libtpu
            # misconfig), not the recoverable wedged-relay hang the budget
            # exists for — don't burn 15 minutes on them.
            hard_failures += 1
            _log(f"probe attempt {attempt}: device init failed "
                 f"(rc={probe.returncode})")
            if hard_failures >= 3:
                _log("3 hard failures: backend is broken, not wedged")
                return False
        except subprocess.TimeoutExpired:
            hard_failures = 0
            _log(f"probe attempt {attempt}: device init hung {per_try:.0f}s")
        remaining = deadline - time.time()
        if remaining <= 1:
            return False
        wait = min(backoff, remaining)
        _log(f"retrying probe in {wait:.0f}s ({remaining:.0f}s budget left)")
        time.sleep(wait)
        backoff = min(backoff * 2, 240.0)


def _run_variant(config, batch_size: int, seq_len: int, steps: int,
                 devices):
    import numpy as np
    import optax

    from tf_yarn_tpu.benchmark import measure_throughput
    from tf_yarn_tpu.models import common
    from tf_yarn_tpu.models.transformer import Transformer

    tokens = np.random.RandomState(0).randint(
        0, config.vocab_size, (batch_size, seq_len), dtype=np.int32
    )
    return measure_throughput(
        Transformer(config),
        common.lm_loss,
        optax.adamw(1e-4),
        {"tokens": tokens},
        steps=steps,
        devices=devices,
    )


def bench_flagship_train():
    if not _probe_backend_alive():
        _log("default backend unreachable (hung device init, budget spent); "
             "forcing CPU")
        os.environ["TPU_YARN_PLATFORM"] = "cpu"

    from tf_yarn_tpu.models.transformer import TransformerConfig
    from tf_yarn_tpu.parallel.mesh import select_devices

    devices = select_devices()
    on_tpu = devices[0].platform == "tpu"
    _log(f"benchmarking on {len(devices)} x {devices[0].device_kind}")

    if on_tpu:
        # remat off: this config's activations fit one chip's HBM, so
        # recompute would only burn MXU cycles.
        base = dict(
            vocab_size=32000, d_model=1024, n_layers=8, n_heads=16,
            n_kv_heads=8, d_ff=4096, max_seq_len=2048, remat=False,
        )
        batch_size, seq_len, steps = 8, 1024, 20
        # Axes: layer-scan on/off (unrolling lets XLA fuse across layer
        # boundaries — measured ~+25% on v5e), attention xla/flash, fused
        # pallas norms on/off.
        variants = [
            ("xla", dict(attention_impl="xla", fused_norms=False)),
            ("xla+fused_norms", dict(attention_impl="xla", fused_norms=True)),
            ("xla+fused+unroll", dict(attention_impl="xla", fused_norms=True,
                                      scan_layers=False)),
            ("flash+fused+unroll", dict(attention_impl="flash",
                                        fused_norms=True, scan_layers=False)),
        ]
    else:  # CPU smoke fallback so the bench always emits a line
        base = None
        batch_size, seq_len, steps = 8, 64, 5
        variants = [("xla", None)]

    table = []
    model_desc = None
    for name, overrides in variants:
        config = (TransformerConfig(**{**base, **overrides})
                  if overrides is not None else TransformerConfig.tiny())
        model_desc = f"d_model={config.d_model}, layers={config.n_layers}"
        try:
            stats = _run_variant(config, batch_size, seq_len, steps, devices)
        except Exception as exc:  # a broken kernel must not kill the bench
            _log(f"variant {name}: FAILED: {type(exc).__name__}: {exc}")
            table.append({"variant": name, "error": f"{exc}"})
            continue
        row = {
            "variant": name,
            "samples_per_sec_per_chip": round(
                stats["samples_per_sec_per_chip"], 3),
            "step_time_ms": round(stats["step_time_ms"], 2),
            "mfu": round(stats["mfu"], 4) if "mfu" in stats else None,
            "final_loss": round(stats["final_loss"], 4),
        }
        table.append(row)
        _log(f"variant {name}: {row['samples_per_sec_per_chip']} samples/s/chip, "
             f"step {row['step_time_ms']}ms, mfu={row['mfu']}")

    ok_rows = [r for r in table if "error" not in r]
    if not ok_rows:
        # Even a fully-failed sweep must emit the one JSON line.
        return {
            "metric": "flagship_train_samples_per_sec_per_chip",
            "value": 0.0,
            "unit": "samples/sec/chip (all variants failed: "
            + "; ".join(str(r.get("error", ""))[:80] for r in table) + ")",
        }
    best = max(ok_rows, key=lambda r: r["samples_per_sec_per_chip"])
    if on_tpu:
        ab_path = os.path.join(os.path.dirname(__file__), "BENCH_AB.json")
        try:
            with open(ab_path, "w") as fh:
                json.dump({
                    "config": {**base, "batch": batch_size, "seq": seq_len},
                    "device": devices[0].device_kind,
                    "rows": table,
                }, fh, indent=1)
            _log(f"A/B table -> {ab_path}")
        except OSError as exc:
            _log(f"could not write A/B table: {exc}")

    result = {
        "metric": "flagship_train_samples_per_sec_per_chip",
        "value": best["samples_per_sec_per_chip"],
        "unit": f"samples/sec/chip ({model_desc}, seq={seq_len}, "
        f"bf16, {'tpu, ' + best['variant'] if on_tpu else 'cpu-fallback'})",
    }
    if best.get("mfu") is not None:
        result["mfu"] = best["mfu"]
    return result


def main() -> None:
    result = bench_flagship_train()
    baseline_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    vs_baseline = 1.0
    if os.path.exists(baseline_path):
        try:
            with open(baseline_path) as fh:
                baseline = json.load(fh)
            if baseline.get("metric") == result["metric"] and baseline.get("value"):
                vs_baseline = round(result["value"] / float(baseline["value"]), 3)
        except (ValueError, OSError):
            pass
    result["vs_baseline"] = vs_baseline
    print(json.dumps(result))


if __name__ == "__main__":
    main()
