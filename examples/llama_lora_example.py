"""LoRA fine-tune of the llama-style decoder — BASELINE.json config 5.

New capability with no reference analog: FSDP+TP mesh, frozen base
weights, LoRA adapters trained, ring attention available by flipping
`attention_impl="ring"` for long sequences over the sp axis.

The default config here is a small decoder so the example runs anywhere;
substitute `TransformerConfig.llama3_8b(lora_rank=16)` on a v5e-16.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("TPU_YARN_VIRTUAL_DEVICES", "8")
os.environ.setdefault("TPU_YARN_PLATFORM", os.environ.get("EXAMPLE_PLATFORM", "cpu"))

MODEL_DIR = os.path.join(tempfile.gettempdir(), "tpu_yarn_llama_lora")


def experiment_fn():
    from tf_yarn_tpu.models.transformer import TransformerConfig, make_experiment
    from tf_yarn_tpu.parallel.mesh import MeshSpec

    config = TransformerConfig(
        vocab_size=1024,
        d_model=256,
        n_layers=4,
        n_heads=8,
        n_kv_heads=4,
        d_ff=512,
        max_seq_len=512,
        lora_rank=8,
    )
    return make_experiment(
        config,
        model_dir=MODEL_DIR,
        train_steps=30,
        batch_size=8,
        seq_len=128,
        learning_rate=1e-4,
        mesh_spec=MeshSpec(dp=2, fsdp=2, tp=2),
        log_every_steps=5,
    )


if __name__ == "__main__":
    from tf_yarn_tpu import TaskSpec, run_on_tpu

    metrics = run_on_tpu(
        experiment_fn, {"worker": TaskSpec(instances=1)}, name="llama_lora"
    )
    print("run metrics:", metrics)

    # Deployment step: fold the trained adapters into the base weights —
    # the merged tree serves under lora_rank=0 with zero adapter math.
    import jax

    jax.config.update("jax_platforms", "cpu")
    import dataclasses

    from tf_yarn_tpu import checkpoint as ckpt_lib
    from tf_yarn_tpu.models.transformer import Transformer, merge_lora

    experiment = experiment_fn()
    step = ckpt_lib.latest_checkpoint_step(MODEL_DIR)
    assert step is not None, "no checkpoint written"
    # Host restore: the ckpt was written by an 8-device worker mesh; the
    # driver merges on its single CPU device (numpy, topology-free).
    state = ckpt_lib.restore_checkpoint_host(MODEL_DIR, step)
    # TrainState.params is the full variables dict ({"params": ...}).
    merged = merge_lora(state["params"], experiment.model.config)
    plain_cfg = dataclasses.replace(experiment.model.config, lora_rank=0)
    import jax.numpy as jnp

    logits = Transformer(plain_cfg).apply(merged, jnp.zeros((1, 8), jnp.int32))
    print("merged adapter model serves plain:", logits.shape)
