"""Online serving demo: continuous batching over a slot grid.

Builds a tiny decoder, starts the serving stack in-process (slot
scheduler + threaded HTTP frontend — the same pieces the `serving` task
type runs through the launcher), fires a burst of concurrent HTTP
requests with mixed prompt/output lengths, and prints each stream plus
the scheduler's tick trace — watch a slot freed by a short request get
re-admitted while longer requests are still decoding.

`python examples/serving_example.py fleet` runs the FLEET variant
instead (docs/Fleet.md): two replicas behind a router task — requests
go through the router's identical `/v1/generate`, then one replica is
killed and the survivor keeps serving (health ejection + failover).

`python examples/serving_example.py --spec` turns on SPECULATIVE
decoding (docs/Serving.md "Speculative decoding"): the n-gram
self-drafter proposes tokens per slot, one windowed program verifies
them, and the repeated-structure request in the burst lands multiple
tokens per tick — the printed trace shows the per-tick accepted
counts, and the streams are identical to the exact path.

`python examples/serving_example.py --tp` runs TENSOR-PARALLEL decode
(docs/Serving.md "Tensor-parallel decode"): the weights and the paged
KV pool shard across 2 (virtual, on CPU) devices, XLA inserts the TP
all-reduces from the placements, and the streams are identical to the
single-device run — the printout shows per-device vs global KV bytes.
"""

import http.client
import json
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("TPU_YARN_PLATFORM", os.environ.get("EXAMPLE_PLATFORM", "cpu"))
if "--tp" in sys.argv[1:] and "--xla_force_host_platform_device_count" \
        not in os.environ.get("XLA_FLAGS", ""):
    # Must land before the first jax call in this process: the tp demo
    # needs 2 devices; on the CPU platform that means virtual host
    # devices (the same switch the test rig's conftest flips).
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    )


def main(spec: bool = False, tp: bool = False) -> None:
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_yarn_tpu.models.decode_engine import DecodeEngine
    from tf_yarn_tpu.models.transformer import Transformer, TransformerConfig
    from tf_yarn_tpu.parallel.mesh import MeshSpec, build_mesh, select_devices
    from tf_yarn_tpu.serving import ServingServer, SlotScheduler

    config = TransformerConfig.tiny(max_seq_len=64, scan_layers=False)
    model = Transformer(config)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    )
    mesh = None
    if tp:
        # Tensor-parallel replica: weights placed by the logical-axis
        # rules, slot KV sharded by kv-heads — the serving task does
        # exactly this from ServingExperiment(mesh_spec=MeshSpec(tp=2)).
        from tf_yarn_tpu import inference

        mesh = build_mesh(MeshSpec(tp=2), select_devices(2))
        params = inference.shard_restored_params(model, params, mesh)
    engine = DecodeEngine(
        model, batch_buckets=(1, 2, 4), prompt_buckets=(4, 8, 16),
        mesh=mesh,
    )

    # Paged KV slots: a global pool of 8-token blocks instead of one
    # full max_seq_len cache per slot — 11 blocks here vs the dense
    # equivalent of 17, with a prefix cache sharing repeated prompt
    # prefixes (docs/Serving.md "Paged KV & prefix cache"). --spec adds
    # speculative decoding: 3 n-gram drafts per slot per tick, verified
    # in one windowed program (docs/Serving.md "Speculative decoding").
    scheduler = SlotScheduler(
        engine, params, max_slots=2,
        kv_layout="paged", block_size=8, num_blocks=11,
        spec_k=3 if spec else 0,
    )
    scheduler.start()
    server = ServingServer(scheduler, "127.0.0.1", 0)
    server.start()
    stats0 = scheduler.stats()
    print(f"serving on {server.endpoint} (grid of {scheduler.max_slots} "
          f"paged slots, {stats0['kv_cache_hbm_bytes']} KV bytes"
          + (f", spec_k={scheduler.spec_k}" if spec else "")
          + (f", tp={stats0['tp_degree']}: "
             f"{stats0['kv_cache_hbm_bytes_per_device']} KV bytes/device"
             if tp else "") + ")")

    rng = np.random.RandomState(0)
    motif = rng.randint(0, 256, 3)
    bodies = [
        {"prompt": rng.randint(0, 256, 5).tolist(), "max_new_tokens": 3},
        # Repeated structure: with --spec the n-gram drafter reads the
        # motif and this request lands multiple tokens per tick.
        {"prompt": np.tile(motif, 3).tolist(), "max_new_tokens": 12},
        {"prompt": rng.randint(0, 256, 3).tolist(), "max_new_tokens": 6},
        {"prompt": rng.randint(0, 256, 7).tolist(), "max_new_tokens": 8},
    ]
    results = {}

    def call(index):
        conn = http.client.HTTPConnection(
            "127.0.0.1", server.port, timeout=300
        )
        conn.request(
            "POST", "/v1/generate", json.dumps(bodies[index]),
            {"Content-Type": "application/json"},
        )
        results[index] = json.loads(conn.getresponse().read())
        conn.close()

    threads = [threading.Thread(target=call, args=(i,)) for i in range(4)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()

    for index, body in enumerate(bodies):
        reply = results[index]
        print(
            f"request {index}: P={len(body['prompt'])} "
            f"max_new={body['max_new_tokens']} -> {reply['tokens']} "
            f"({reply['finish_reason']}, ttft {reply['ttft_s']:.3f}s)"
        )

    print("\ntick trace (admit/retire interleaving = continuous batching):")
    for entry in scheduler.trace:
        if entry["admitted"] or entry["retired"]:
            print(f"  {entry}")
    if spec:
        accepted = [n for t in scheduler.trace
                    for n in t.get("accepted", {}).values()]
        stats = scheduler.stats()["spec"]
        print(f"\nspeculative: accept_rate={stats['accept_rate']}, "
              f"max tokens landed in one tick="
              f"{max(accepted) if accepted else 0}")

    server.stop()
    scheduler.close()


def fleet() -> None:
    """Two serving replicas behind a fleet router (docs/Fleet.md):
    discovery through the KV endpoint events, health-probed admission,
    least-loaded balancing, and kill-one-replica failover — all the
    pieces `fleet_topology` launches, in one process."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_yarn_tpu import event
    from tf_yarn_tpu.coordination.kv import InProcessKV
    from tf_yarn_tpu.fleet import ReplicaRegistry, RouterServer, make_policy
    from tf_yarn_tpu.models.decode_engine import DecodeEngine
    from tf_yarn_tpu.models.transformer import Transformer, TransformerConfig
    from tf_yarn_tpu.serving import ServingServer, SlotScheduler

    config = TransformerConfig.tiny(max_seq_len=64, scan_layers=False)
    model = Transformer(config)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    )
    # One engine shared by both replicas: compiles are paid once.
    engine = DecodeEngine(
        model, batch_buckets=(1, 2, 4), prompt_buckets=(4, 8, 16)
    )
    kv = InProcessKV()
    replicas = []
    for index in range(2):
        scheduler = SlotScheduler(engine, params, max_slots=2)
        scheduler.start()
        server = ServingServer(scheduler, "127.0.0.1", 0)
        server.start()
        task = f"serving:{index}"
        # The discovery protocol the launcher's serving tasks speak.
        event.serving_endpoint_event(kv, task, server.endpoint)
        replicas.append((task, scheduler, server))
        print(f"replica {task} on {server.endpoint}")

    registry = ReplicaRegistry(
        kv, tasks=[task for task, _, _ in replicas], probe_interval_s=0.2
    )
    registry.refresh(force=True)
    router = RouterServer(
        registry, make_policy("least_loaded"), "127.0.0.1", 0, retries=3
    )
    router.start()
    print(f"router on {router.endpoint} "
          f"({len(registry.healthy())} replicas healthy)")

    def ask(tag):
        rng = np.random.RandomState(hash(tag) % 2**16)
        body = {"prompt": rng.randint(0, 256, 5).tolist(),
                "max_new_tokens": 6}
        conn = http.client.HTTPConnection(
            "127.0.0.1", router.port, timeout=300
        )
        conn.request(
            "POST", "/v1/generate", json.dumps(body),
            {"Content-Type": "application/json"},
        )
        reply = json.loads(conn.getresponse().read())
        conn.close()
        print(f"  {tag}: {reply['tokens']} ({reply['finish_reason']})")

    print("\nfour requests through the router:")
    for index in range(4):
        ask(f"request {index}")
    print("routed:", router.stats()["routed_requests"])

    task0, scheduler0, server0 = replicas[0]
    print(f"\nkilling {task0} — the fleet keeps serving:")
    server0.stop()
    scheduler0.close()
    for index in range(3):
        ask(f"after-kill {index}")
    stats = router.stats()
    print("routed:", stats["routed_requests"])
    print("replica states:",
          {t: r["state"] for t, r in stats["replicas"].items()})

    router.stop()
    for _task, scheduler, server in replicas[1:]:
        server.stop()
        scheduler.close()


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "fleet":
        fleet()
    else:
        main(spec="--spec" in sys.argv[1:], tp="--tp" in sys.argv[1:])
