"""Online serving demo: continuous batching over a slot grid.

Builds a tiny decoder, starts the serving stack in-process (slot
scheduler + threaded HTTP frontend — the same pieces the `serving` task
type runs through the launcher), fires a burst of concurrent HTTP
requests with mixed prompt/output lengths, and prints each stream plus
the scheduler's tick trace — watch a slot freed by a short request get
re-admitted while longer requests are still decoding.
"""

import http.client
import json
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("TPU_YARN_PLATFORM", os.environ.get("EXAMPLE_PLATFORM", "cpu"))


def main() -> None:
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_yarn_tpu.models.decode_engine import DecodeEngine
    from tf_yarn_tpu.models.transformer import Transformer, TransformerConfig
    from tf_yarn_tpu.serving import ServingServer, SlotScheduler

    config = TransformerConfig.tiny(max_seq_len=64, scan_layers=False)
    model = Transformer(config)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    )
    engine = DecodeEngine(
        model, batch_buckets=(1, 2, 4), prompt_buckets=(4, 8, 16)
    )

    # Paged KV slots: a global pool of 8-token blocks instead of one
    # full max_seq_len cache per slot — 11 blocks here vs the dense
    # equivalent of 17, with a prefix cache sharing repeated prompt
    # prefixes (docs/Serving.md "Paged KV & prefix cache").
    scheduler = SlotScheduler(
        engine, params, max_slots=2,
        kv_layout="paged", block_size=8, num_blocks=11,
    )
    scheduler.start()
    server = ServingServer(scheduler, "127.0.0.1", 0)
    server.start()
    print(f"serving on {server.endpoint} (grid of {scheduler.max_slots} "
          f"paged slots, {scheduler.stats()['kv_cache_hbm_bytes']} KV bytes)")

    rng = np.random.RandomState(0)
    bodies = [
        {"prompt": rng.randint(0, 256, 5).tolist(), "max_new_tokens": 3},
        {"prompt": rng.randint(0, 256, 9).tolist(), "max_new_tokens": 12},
        {"prompt": rng.randint(0, 256, 3).tolist(), "max_new_tokens": 6},
        {"prompt": rng.randint(0, 256, 7).tolist(), "max_new_tokens": 8},
    ]
    results = {}

    def call(index):
        conn = http.client.HTTPConnection(
            "127.0.0.1", server.port, timeout=300
        )
        conn.request(
            "POST", "/v1/generate", json.dumps(bodies[index]),
            {"Content-Type": "application/json"},
        )
        results[index] = json.loads(conn.getresponse().read())
        conn.close()

    threads = [threading.Thread(target=call, args=(i,)) for i in range(4)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()

    for index, body in enumerate(bodies):
        reply = results[index]
        print(
            f"request {index}: P={len(body['prompt'])} "
            f"max_new={body['max_new_tokens']} -> {reply['tokens']} "
            f"({reply['finish_reason']}, ttft {reply['ttft_s']:.3f}s)"
        )

    print("\ntick trace (admit/retire interleaving = continuous batching):")
    for entry in scheduler.trace:
        if entry["admitted"] or entry["retired"]:
            print(f"  {entry}")

    server.stop()
    scheduler.close()


if __name__ == "__main__":
    main()
