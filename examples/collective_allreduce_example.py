"""Synchronous data parallelism over the mesh (reference analogs:
examples/collective_all_reduce_example.py and
native_keras_with_gloo_example.py — both Horovod/Gloo there).

On TPU there is no rendezvous server, no ring formation, no
DistributedOptimizer wrapper: data parallelism is a mesh axis, and the
gradient allreduce is compiled into the train step by XLA. This example
makes that explicit by training the BERT-tiny classifier data-parallel
over every available device.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("TPU_YARN_VIRTUAL_DEVICES", "8")
os.environ.setdefault("TPU_YARN_PLATFORM", os.environ.get("EXAMPLE_PLATFORM", "cpu"))


def experiment_fn():
    from tf_yarn_tpu.models import bert
    from tf_yarn_tpu.parallel.mesh import MeshSpec

    return bert.make_experiment(
        bert.BertConfig.tiny(),
        train_steps=40,
        batch_size=64,
        seq_len=32,
        mesh_spec=MeshSpec(dp=8),  # pure DP: params replicated, grads psum'd
        log_every_steps=10,
    )


if __name__ == "__main__":
    from tf_yarn_tpu import TaskSpec, run_on_tpu

    metrics = run_on_tpu(
        experiment_fn, {"worker": TaskSpec(instances=1)}, name="allreduce_dp"
    )
    print("run metrics:", metrics)
