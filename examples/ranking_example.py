"""Online ranking demo: micro-batched DLRM scoring behind /v1/rank.

Builds a tiny DLRM, starts the ranking stack in-process (fill-or-timeout
MicroBatchScheduler + threaded HTTP frontend — the same pieces the
`rank` task type runs through the launcher), fires a burst of concurrent
HTTP requests with mixed row counts, and prints each request's scores
plus the scheduler snapshot — watch `ticks` come out well below the
request count (requests coalesced into shared compiled forwards) and
`forward_cache_hits` dwarf `forward_compiles` (the bucketed programs
compile once at warmup, then every tick is a cache hit).

Every score is also checked bitwise against a direct jitted forward of
the same params — micro-batching and ceil-padding to a batch bucket are
performance decisions, not accuracy decisions (docs/Ranking.md
"Correctness contract").

`python examples/ranking_example.py --tp` runs the EMBEDDING-SHARDED
variant (docs/Ranking.md "Sharding layout"): the stacked embedding
table splits row-wise across 2 (virtual, on CPU) devices, XLA inserts
the one lookup all-reduce from the placements, and the scores are
bitwise identical to the unsharded run — the printout shows per-device
vs total parameter bytes.
"""

import http.client
import json
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("TPU_YARN_PLATFORM", os.environ.get("EXAMPLE_PLATFORM", "cpu"))
if "--tp" in sys.argv[1:] and "--xla_force_host_platform_device_count" \
        not in os.environ.get("XLA_FLAGS", ""):
    # Must land before the first jax call in this process: the tp demo
    # needs 2 devices; on the CPU platform that means virtual host
    # devices (the same switch the test rig's conftest flips).
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    )


def main(tp: bool = False) -> None:
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_yarn_tpu.models.dlrm import DLRM, DLRMConfig
    from tf_yarn_tpu.models.rank_engine import RankEngine
    from tf_yarn_tpu.parallel.mesh import MeshSpec, build_mesh, select_devices
    from tf_yarn_tpu.ranking import MicroBatchScheduler, RankServer

    # float32 so the JSON round-trip is exact and the bitwise check
    # below can compare served floats to the direct forward directly.
    config = DLRMConfig.tiny(dtype=jnp.float32)
    model = DLRM(config)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, len(config.table_sizes)), jnp.int32),
        jnp.zeros((1, config.n_dense), jnp.float32),
    ))
    mesh = None
    if tp:
        # Embedding-sharded replica: the table's rows split over the tp
        # axis, everything else replicates — the rank task does exactly
        # this from RankingExperiment(mesh_spec=MeshSpec(tp=2)).
        mesh = build_mesh(MeshSpec(tp=2), select_devices(2))
    engine = RankEngine(model, batch_buckets=(1, 2, 4, 8), mesh=mesh)

    # max_wait_ms=5 is the coalescing window: a request waits up to 5ms
    # for company before its tick fires (docs/Ranking.md "Micro-batch
    # tuning"; `benchmarks/run.py rank` sweeps this knob).
    scheduler = MicroBatchScheduler(
        engine, params, max_batch=8, max_wait_ms=5.0
    )
    compiles = engine.warmup(scheduler.params, max_batch=8)
    scheduler.start()
    server = RankServer(scheduler)
    server.start()
    stats0 = scheduler.stats()
    print(f"ranking on {server.endpoint} (max_batch=8, max_wait_ms=5.0, "
          f"{compiles} bucket programs warmed"
          + (f", tp={stats0['tp_degree']}: "
             f"{stats0['params_hbm_bytes_per_device']} param bytes/device"
             if tp else "") + ")")

    rng = np.random.RandomState(0)
    n_tables = len(config.table_sizes)
    bodies = []
    for batch in (1, 3, 2, 4, 1, 3):
        bodies.append({
            "cat": rng.randint(0, 1_000_000, (batch, n_tables)).tolist(),
            "dense": rng.randn(batch, config.n_dense).round(3).tolist(),
        })
    results = {}

    def call(index):
        conn = http.client.HTTPConnection(
            "127.0.0.1", server.port, timeout=300
        )
        conn.request(
            "POST", "/v1/rank", json.dumps(bodies[index]),
            {"Content-Type": "application/json"},
        )
        results[index] = json.loads(conn.getresponse().read())
        conn.close()

    threads = [threading.Thread(target=call, args=(i,))
               for i in range(len(bodies))]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()

    # The parity oracle: a plain jitted forward on the exact (unpadded,
    # uncoalesced) batch. Served scores must match it bit for bit.
    direct = jax.jit(model.apply)
    for index, body in enumerate(bodies):
        reply = results[index]
        want = np.asarray(direct(
            scheduler.params,
            jnp.asarray(body["cat"], jnp.int32),
            jnp.asarray(body["dense"], jnp.float32),
        ), np.float32).squeeze(-1)
        bitwise = reply["scores"] == [float(v) for v in want]
        print(f"request {index}: rows={len(body['cat'])} -> "
              f"{[round(s, 4) for s in reply['scores']]} "
              f"({reply['finish_reason']}, bitwise={bitwise})")
        assert bitwise, f"request {index} diverged from the direct forward"

    stats = scheduler.stats()
    print(f"\n{stats['requests_total']} requests, {stats['rows_scored']} "
          f"rows in {stats['ticks']} ticks "
          f"(avg {stats['avg_batch_rows']} rows/tick — coalescing); "
          f"engine: {stats['rank_engine']['forward_compiles']} compiles, "
          f"{stats['rank_engine']['forward_cache_hits']} cache hits")

    server.stop()
    scheduler.close()


if __name__ == "__main__":
    main(tp="--tp" in sys.argv[1:])
