"""PytorchExperiment run (reference analog: examples/pytorch/pytorch_example.py).

DDP training of a small CNN through the pytorch worker: gloo locally,
torch-xla's "xla" backend automatically on TPU hosts.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MODEL_DIR = os.path.join(tempfile.gettempdir(), "tpu_yarn_pytorch")


def experiment_fn():
    import torch

    from tf_yarn_tpu.pytorch import DataLoaderArgs, PytorchExperiment

    x = torch.randn(256, 1, 16, 16)
    y = (x.mean(dim=(1, 2, 3)) > 0).long()
    dataset = torch.utils.data.TensorDataset(x, y)

    model = torch.nn.Sequential(
        torch.nn.Conv2d(1, 8, 3, padding=1),
        torch.nn.ReLU(),
        torch.nn.AdaptiveAvgPool2d(1),
        torch.nn.Flatten(),
        torch.nn.Linear(8, 2),
    )

    def main_fn(model, loader, device, rank, tb_writer):
        opt = torch.optim.Adam(model.parameters(), lr=1e-3)
        loss_fn = torch.nn.CrossEntropyLoss()
        for epoch in range(2):
            for step, (xb, yb) in enumerate(loader):
                opt.zero_grad()
                loss = loss_fn(model(xb.to(device)), yb.to(device))
                loss.backward()
                opt.step()
            if rank == 0:
                print(f"epoch {epoch}: loss={loss.item():.4f}")
                if tb_writer is not None:
                    tb_writer.add_scalar("loss", loss.item(), epoch)
        if rank == 0:
            from tf_yarn_tpu.utils import model_ckpt

            model_ckpt.save_ckpt(MODEL_DIR, model, opt, epoch=2)

    return PytorchExperiment(
        model=model,
        main_fn=main_fn,
        train_dataset=dataset,
        dataloader_args=DataLoaderArgs(batch_size=32),
        tensorboard_log_dir=os.path.join(MODEL_DIR, "tb"),
    )


if __name__ == "__main__":
    from tf_yarn_tpu import TaskSpec
    from tf_yarn_tpu.pytorch import run_on_tpu

    metrics = run_on_tpu(
        experiment_fn, {"worker": TaskSpec(instances=2)}, name="pytorch_ddp"
    )
    print("run metrics:", metrics)
