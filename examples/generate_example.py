"""Train-then-generate: the full lifecycle of the decoder family.

Trains a tiny character-level LM on a repeating pattern, checkpoints it,
restores the checkpoint on the host, and generates continuations with the
KV-cache decode path — the inference counterpart of llama_lora_example.py.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("TPU_YARN_VIRTUAL_DEVICES", "8")
os.environ.setdefault("TPU_YARN_PLATFORM", os.environ.get("EXAMPLE_PLATFORM", "cpu"))

MODEL_DIR = os.path.join(tempfile.gettempdir(), "tpu_yarn_generate_demo")


def main() -> None:
    import numpy as np

    from tf_yarn_tpu import checkpoint as ckpt
    from tf_yarn_tpu.experiment import as_core_experiment
    from tf_yarn_tpu.models.generate import generate
    from tf_yarn_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        make_experiment,
    )
    from tf_yarn_tpu.parallel.mesh import MeshSpec
    from tf_yarn_tpu.training import train_and_evaluate

    pattern = np.tile(np.arange(1, 9, dtype=np.int32), 16)

    def input_fn():
        while True:
            starts = np.random.randint(0, 8, 8)
            yield {
                "tokens": np.stack(
                    [pattern[s:s + 32] for s in starts]
                ).astype(np.int32)
            }

    config = TransformerConfig.tiny(vocab_size=16, max_seq_len=64)
    experiment = make_experiment(
        config,
        model_dir=MODEL_DIR,
        train_steps=150,
        batch_size=8,
        seq_len=32,
        learning_rate=3e-3,
        mesh_spec=MeshSpec(dp=8),
        input_fn=input_fn,
        log_every_steps=50,
    )
    metrics = train_and_evaluate(as_core_experiment(experiment))
    print(f"trained to loss {metrics['loss']:.4f}")

    state = ckpt.restore_checkpoint_host(MODEL_DIR, 150)
    params = {"params": state["params"]["params"]}
    model = Transformer(config)
    prompt = np.asarray([[1, 2, 3, 4]], np.int32)
    out = generate(model, params, prompt, max_new_tokens=8, temperature=0.0)
    print("greedy continuation of [1,2,3,4]:", np.asarray(out)[0].tolist())


if __name__ == "__main__":
    main()
