"""Side-car evaluation + TensorBoard (reference analog:
examples/id_estimator_example.py topology with evaluator + tensorboard
tasks from examples/keras_example.py).

Three tasks: a worker training with periodic checkpoints, an evaluator
polling the checkpoint dir on CPU, and a TensorBoard service advertising
its URL through the KV store (printed once by the driver).
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MODEL_DIR = os.path.join(tempfile.gettempdir(), "tpu_yarn_sidecar_demo")


def experiment_fn():
    from tf_yarn_tpu.models import mnist
    from tf_yarn_tpu.parallel.mesh import MeshSpec

    return mnist.make_experiment(
        model_dir=MODEL_DIR,
        train_steps=60,
        batch_size=64,
        mesh_spec=MeshSpec(fsdp=8),
        checkpoint_every_steps=20,
        log_every_steps=20,
    )


if __name__ == "__main__":
    from tf_yarn_tpu import NodeLabel, TaskSpec, run_on_tpu

    metrics = run_on_tpu(
        experiment_fn,
        {
            "worker": TaskSpec(instances=1),
            "evaluator": TaskSpec(instances=1, label=NodeLabel.CPU),
            "tensorboard": TaskSpec(
                instances=1,
                label=NodeLabel.CPU,
                tb_model_dir=MODEL_DIR,
                tb_termination_timeout_seconds=0,
            ),
        },
        env={
            "TPU_YARN_PLATFORM": os.environ.get("EXAMPLE_PLATFORM", "cpu"),
            "TPU_YARN_VIRTUAL_DEVICES": "8",
            "TPU_YARN_EVAL_IDLE_TIMEOUT": "60",
        },
        name="sidecar_demo",
    )
    print("run metrics:", metrics)
