"""MLflow end-to-end run with a post-run assertion that metrics landed
(reference analog: examples/mlflow_example.py:45-119).

Configures a file-backed MLflow tracking store, runs a small
JaxExperiment through `run_on_tpu`, then queries the store back through
the MlflowClient API and *asserts* the training metrics were recorded —
the part the reference does over REST (mlflow_example.py:113-119).

Degrades gracefully when the `mlflow` package is absent (the shim
no-ops): the run still completes, and the script says why it could not
assert.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("TPU_YARN_VIRTUAL_DEVICES", "8")
os.environ.setdefault("TPU_YARN_PLATFORM", os.environ.get("EXAMPLE_PLATFORM", "cpu"))

MODEL_DIR = os.path.join(tempfile.gettempdir(), "tpu_yarn_mlflow_example")
TRACKING_DIR = os.path.join(tempfile.gettempdir(), "tpu_yarn_mlflow_store")


def experiment_fn():
    import numpy as np
    import optax

    from tf_yarn_tpu import JaxExperiment, TrainParams
    from tf_yarn_tpu.models import common
    from tf_yarn_tpu.models.mnist import DenseClassifier
    from tf_yarn_tpu.parallel.mesh import MeshSpec

    rng = np.random.RandomState(0)

    def batches():
        while True:
            yield {
                "x": rng.randn(64, 784).astype(np.float32),
                "y": rng.randint(0, 10, 64).astype(np.int32),
            }

    return JaxExperiment(
        model=DenseClassifier(num_classes=10),
        model_dir=MODEL_DIR,
        train_params=TrainParams(
            train_steps=40, checkpoint_every_steps=20, log_every_steps=10
        ),
        train_input_fn=batches,
        optimizer=optax.adam(1e-3),
        loss_fn=common.classification_loss,
        mesh_spec=MeshSpec(fsdp=8),
    )


def main() -> None:
    try:
        import mlflow
    except ImportError:
        mlflow = None
        print("mlflow not installed: running with the no-op shim "
              "(no post-run assertion possible)")

    run_id = None
    if mlflow is not None:
        mlflow.set_tracking_uri(f"file://{TRACKING_DIR}")
        mlflow.set_experiment("tpu_yarn_mlflow_example")
        run = mlflow.start_run()
        run_id = run.info.run_id

    from tf_yarn_tpu import TaskSpec, run_on_tpu

    metrics = run_on_tpu(
        experiment_fn,
        {"worker": TaskSpec(instances=1)},
        name="mlflow_example",
    )
    print("run metrics:", metrics)

    if mlflow is None:
        return
    mlflow.end_run()

    # Post-run assertion (reference: mlflow_example.py:113-119): read the
    # run back out of the tracking store and check our metrics landed.
    from mlflow.tracking import MlflowClient

    client = MlflowClient()
    logged = client.get_run(run_id).data.metrics
    print("mlflow metrics:", sorted(logged))
    step_keys = [k for k in logged if k.startswith("steps_per_sec")]
    assert step_keys, f"no steps_per_sec_* metric in mlflow run: {sorted(logged)}"
    history = client.get_metric_history(run_id, step_keys[0])
    assert history, "metric history empty"
    print(f"asserted: {step_keys[0]} logged {len(history)} point(s) "
          f"to {mlflow.get_tracking_uri()}")


if __name__ == "__main__":
    main()
