"""KerasExperiment-shaped run (reference analog: examples/keras_example.py).

A dense MNIST-style classifier through the Keras experiment surface:
separate feature/target streams, validation stream, checkpoints to
model_dir — trained by the pjit loop on whatever devices are present.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("TPU_YARN_VIRTUAL_DEVICES", "8")
os.environ.setdefault("TPU_YARN_PLATFORM", os.environ.get("EXAMPLE_PLATFORM", "cpu"))

MODEL_DIR = os.path.join(tempfile.gettempdir(), "tpu_yarn_mnist_keras")


def experiment_fn():
    import numpy as np
    import optax

    from tf_yarn_tpu import KerasExperiment, TrainParams
    from tf_yarn_tpu.models import common
    from tf_yarn_tpu.models.mnist import DenseClassifier
    from tf_yarn_tpu.parallel.mesh import MeshSpec

    rng = np.random.RandomState(0)

    def features():
        while True:
            yield {"x": rng.randn(64, 784).astype(np.float32)}

    def targets():
        while True:
            yield rng.randint(0, 10, 64).astype(np.int32)

    def validation():
        for _ in range(4):
            yield {
                "x": rng.randn(64, 784).astype(np.float32),
                "y": rng.randint(0, 10, 64).astype(np.int32),
            }

    return KerasExperiment(
        model=DenseClassifier(num_classes=10),
        model_dir=MODEL_DIR,
        train_params=TrainParams(
            train_steps=50, checkpoint_every_steps=25, log_every_steps=10
        ),
        input_data_fn=features,
        target_data_fn=targets,
        validation_data_fn=validation,
        optimizer=optax.adam(1e-3),
        loss_fn=common.classification_loss,
        mesh_spec=MeshSpec(fsdp=8),
    )


if __name__ == "__main__":
    from tf_yarn_tpu import TaskSpec, run_on_tpu

    metrics = run_on_tpu(
        experiment_fn,
        {"worker": TaskSpec(instances=1)},
        name="mnist_keras",
    )
    print("run metrics:", metrics)
    print("checkpoints in", MODEL_DIR, os.listdir(MODEL_DIR))
