"""Estimator-shaped run (reference analog: examples/linear_classifier_example.py).

Hashed sparse logistic regression via the Experiment(estimator,
train_spec, eval_spec) triple — the reference's LinearClassifier-on-clicks
workflow with the weight table mesh-sharded instead of parameter-served.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("TPU_YARN_VIRTUAL_DEVICES", "8")
os.environ.setdefault("TPU_YARN_PLATFORM", os.environ.get("EXAMPLE_PLATFORM", "cpu"))

MODEL_DIR = os.path.join(tempfile.gettempdir(), "tpu_yarn_linear")


def experiment_fn():
    import numpy as np
    import optax

    from tf_yarn_tpu import Estimator, EvalSpec, ExperimentSpec, TrainSpec
    from tf_yarn_tpu.models import common
    from tf_yarn_tpu.models.linear import HashedLinearClassifier, LinearConfig
    from tf_yarn_tpu.parallel.mesh import MeshSpec

    config = LinearConfig(n_buckets=2**16, n_features=26)
    rng = np.random.RandomState(0)
    hot = rng.randint(0, config.n_buckets, 128)

    def batches(seed):
        r = np.random.RandomState(seed)
        while True:
            x = r.randint(0, config.n_buckets, (512, config.n_features))
            y = (np.isin(x, hot).sum(axis=1) > 0).astype(np.int32)
            yield {"x": x.astype(np.int32), "y": y}

    model = HashedLinearClassifier(config)
    estimator = Estimator(
        model=model,
        loss_fn=common.binary_logistic_loss,
        optimizer=optax.adagrad(0.1),
        model_dir=MODEL_DIR,
        init_fn=lambda rng_, batch: model.init(rng_, batch["x"]),
        mesh_spec=MeshSpec(fsdp=8),
    )
    return ExperimentSpec(
        estimator=estimator,
        train_spec=TrainSpec(input_fn=lambda: batches(0), max_steps=100),
        eval_spec=EvalSpec(input_fn=lambda: batches(1), steps=5),
    )


if __name__ == "__main__":
    from tf_yarn_tpu import TaskSpec, run_on_tpu

    metrics = run_on_tpu(
        experiment_fn, {"worker": TaskSpec(instances=1)}, name="linear_clf"
    )
    print("run metrics:", metrics)
