"""Generic fn-of-rank mode (reference analog:
examples/pytorch/pytorch_distributed_example.py using tf_yarn.distributed).

The experiment is just a function receiving TaskParameters — no model
plumbing; every process does whatever it wants with its rank.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def experiment_fn():
    def run(params):
        print(
            f"hello from {params.task_type}:{params.task_id} "
            f"rank={params.rank}/{params.world_size} "
            f"master={params.master_addr}:{params.master_port}"
        )

    return run


if __name__ == "__main__":
    from tf_yarn_tpu import TaskSpec, run_on_tpu

    run_on_tpu(
        experiment_fn,
        {"worker": TaskSpec(instances=2, nb_proc_per_worker=2)},
        custom_task_module="tf_yarn_tpu.tasks.distributed",
        name="distributed_fn",
    )
