#!/bin/sh
# Run every example end-to-end on this machine (the role of the
# reference's examples/run_examples.sh + run_pytorch_examples.sh real-
# cluster matrices, shrunk to the local integration surface).
set -e
cd "$(dirname "$0")/.."
export PYTHONPATH="$PWD:$PYTHONPATH"

for example in \
    distributed_fn_example \
    mnist_keras_example \
    linear_classifier_example \
    dlrm_example \
    mlflow_example \
    collective_allreduce_example \
    llama_lora_example \
    pytorch_example \
    evaluator_sidecar_example \
    ship_requirements_example \
    generate_example
do
    echo "=== $example ==="
    python "examples/$example.py"
done
echo "all examples passed"
