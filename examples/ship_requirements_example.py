"""Shipping third-party deps with the run (reference analog: the pex
auto-upload in tf_yarn's client — reference client.py:421-424 ships the
WHOLE interpreter env; here only the delta travels as a wheelhouse).

A worker image that lacks a library the experiment imports would die at
unpickle; `requirements=` resolves wheels driver-side and workers
`pip install --no-index` them before unpickling. This example runs
fully offline by hand-building the wheel and passing it via
`wheels_dir=` (the air-gapped path); with driver egress you would pass
just `requirements=["mylib==1.2"]`.
"""

import os
import sys
import tempfile
import zipfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_demo_wheel(out_dir: str) -> None:
    """A minimal local wheel standing in for a real `pip download`."""
    name, version = "shippeddemo", "1.0"
    info = f"{name}-{version}.dist-info"
    wheel = os.path.join(out_dir, f"{name}-{version}-py3-none-any.whl")
    with zipfile.ZipFile(wheel, "w") as zf:
        zf.writestr(f"{name}.py", "GREETING = 'imported from a shipped wheel'\n")
        zf.writestr(f"{info}/METADATA",
                    f"Metadata-Version: 2.1\nName: {name}\nVersion: {version}\n")
        zf.writestr(f"{info}/WHEEL",
                    "Wheel-Version: 1.0\nGenerator: example\n"
                    "Root-Is-Purelib: true\nTag: py3-none-any\n")
        zf.writestr(f"{info}/RECORD", f"{name}.py,,\n{info}/METADATA,,\n"
                    f"{info}/WHEEL,,\n{info}/RECORD,,\n")


def experiment_fn():
    def run(params):
        import shippeddemo  # only importable because the wheel shipped

        print(f"rank {params.rank}: {shippeddemo.GREETING}")

    return run


if __name__ == "__main__":
    from tf_yarn_tpu import TaskSpec, run_on_tpu

    with tempfile.TemporaryDirectory() as wheels:
        _make_demo_wheel(wheels)
        run_on_tpu(
            experiment_fn,
            {"worker": TaskSpec(instances=2)},
            custom_task_module="tf_yarn_tpu.tasks.distributed",
            # ship_code=True: the LocalBackend used by this example does
            # not ship by default; remote backends do.
            ship_code=True,
            requirements=["shippeddemo"],
            wheels_dir=wheels,
            name="ship_requirements",
        )
    print("ship_requirements_example OK")
