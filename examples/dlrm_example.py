"""Deep CTR (DLRM) on the Criteo-clicks shape — the deep sibling of
linear_classifier_example.py (reference analog:
examples/linear_classifier_example.py, whose LinearClassifier is the
shallow version of this workload).

Shows the stacked mesh-sharded embedding: 8 categorical tables live in
one fsdp-sharded param, dense features feed a bottom MLP, and pairwise
feature interaction runs as a single batched matmul.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("TPU_YARN_VIRTUAL_DEVICES", "8")
os.environ.setdefault("TPU_YARN_PLATFORM", os.environ.get("EXAMPLE_PLATFORM", "cpu"))


def experiment_fn():
    from tf_yarn_tpu.models import dlrm
    from tf_yarn_tpu.parallel.mesh import MeshSpec

    config = dlrm.DLRMConfig(
        table_sizes=(4096,) * 8,
        embed_dim=32,
        n_dense=8,
        bottom_mlp=(128,),
        top_mlp=(128,),
    )
    return dlrm.make_experiment(
        config,
        train_steps=120,
        batch_size=512,
        learning_rate=0.1,
        mesh_spec=MeshSpec(dp=2, fsdp=4),
    )


if __name__ == "__main__":
    from tf_yarn_tpu import TaskSpec, run_on_tpu

    metrics = run_on_tpu(
        experiment_fn, {"worker": TaskSpec(instances=1)}, name="dlrm"
    )
    print("run metrics:", metrics)
