"""BASELINE.json benchmark suite: one JSON line per config.

The five configs BASELINE.md tracks (Keras-MNIST-dense, LinearClassifier
clicks, BERT-base, ResNet-50, Llama-LoRA) plus the additions this repo
measures beyond them: dlrm_clicks, vit_base, long_context, decode (bf16
vs int8 KV cache), and the ICI allreduce microbench. Sizes are
TPU-realistic when a TPU is present and tiny on the CPU rig (`--cpu`
forces the latter).

    python benchmarks/run.py                 # all configs
    python benchmarks/run.py bert_base       # one config
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _on_tpu() -> bool:
    import jax

    from tf_yarn_tpu.parallel.mesh import select_devices

    return select_devices()[0].platform == "tpu"


def _best_of_variants(variants, run_one):
    """Shared A/B sweep shape: run each (name, spec), keep per-variant
    samples/sec + MFU rows, return the best run's full stats with the
    rows attached. One bad variant never kills the sweep."""
    rows, best = {}, None
    for name, spec in variants:
        try:
            stats = run_one(spec)
        except Exception as exc:
            rows[name] = {"error": str(exc)[:160]}
            continue
        rows[name] = {
            "samples_per_sec_per_chip": stats["samples_per_sec_per_chip"],
            "mfu": stats.get("mfu"),
        }
        if best is None or (stats["samples_per_sec_per_chip"]
                            > best["samples_per_sec_per_chip"]):
            best = dict(stats, variant=name)
    if best is None:
        return {"variants": rows}
    best["variants"] = rows
    return best


def bench_mnist_dense(tpu: bool):
    import numpy as np
    import optax

    from tf_yarn_tpu.benchmark import measure_throughput
    from tf_yarn_tpu.models import common
    from tf_yarn_tpu.models.mnist import DenseClassifier

    batch = 512 if tpu else 64
    rng = np.random.RandomState(0)
    return measure_throughput(
        DenseClassifier(),
        common.classification_loss,
        optax.adam(1e-3),
        {
            "x": rng.randn(batch, 784).astype(np.float32),
            "y": rng.randint(0, 10, batch).astype(np.int32),
        },
    )


def bench_linear_clicks(tpu: bool):
    import numpy as np
    import optax

    from tf_yarn_tpu.benchmark import measure_throughput
    from tf_yarn_tpu.models import common
    from tf_yarn_tpu.models.linear import HashedLinearClassifier, LinearConfig

    config = LinearConfig(n_buckets=2**20 if tpu else 2**12, n_features=26)
    batch = 4096 if tpu else 256
    rng = np.random.RandomState(0)
    model = HashedLinearClassifier(config)
    return measure_throughput(
        model,
        common.binary_logistic_loss,
        optax.adagrad(0.05),
        {
            "x": rng.randint(0, config.n_buckets, (batch, 26)).astype(np.int32),
            "y": rng.randint(0, 2, batch).astype(np.int32),
        },
    )


def bench_bert_base(tpu: bool):
    import numpy as np
    import optax

    from tf_yarn_tpu.benchmark import measure_throughput
    from tf_yarn_tpu.models import bert

    # b64 from the round-2 sweep: b16 left the MXU underfed (MFU 0.27 ->
    # 0.46); s128 is the classic fine-tune shape. On TPU the fused pallas
    # LayerNorm (ops/layernorm.py) rides as an A/B variant.
    batch, seq = (64, 128) if tpu else (8, 32)
    rng = np.random.RandomState(0)

    def loss_fn(model, params, batch, rng_, train=True):
        import jax.numpy as jnp

        logits = model.apply(
            params, batch["x"], rngs={"dropout": rng_}, deterministic=not train
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]
        ).mean()
        return loss, {"accuracy": jnp.mean(jnp.argmax(logits, -1) == batch["y"])}

    def run_one(variant):
        from tf_yarn_tpu.benchmark import kernel_bwd_env

        fused, kernel_bwd = variant
        config = (bert.BertConfig.base(fused_norms=fused) if tpu
                  else bert.BertConfig.tiny(fused_norms=fused))
        model = bert.BertClassifier(config)
        with kernel_bwd_env(kernel_bwd):
            return measure_throughput(
                model,
                loss_fn,
                optax.adamw(2e-5),
                {
                    "x": rng.randint(
                        0, config.vocab_size, (batch, seq)).astype(np.int32),
                    "y": rng.randint(
                        0, config.num_classes, batch).astype(np.int32),
                },
                init_fn=lambda r, b: model.init(r, b["x"]),
                steps=10 if tpu else 5,
            )

    # Post-LN BERT is the norm-heaviest family (2 norms/layer + embedding
    # norm): fused_ln_fwd isolates the forward kernel, fused_ln adds the
    # dx backward kernels — the pair answers whether the bwd fusion moves
    # the 0.456 MFU (VERDICT r4 item 8).
    variants = ([("base", (False, False)),
                 ("fused_ln_fwd", (True, False)),
                 ("fused_ln", (True, True))] if tpu
                else [("base", (False, False))])
    return _best_of_variants(variants, run_one)


def bench_resnet50(tpu: bool):
    """A/Bs the stem (classic conv7x7s2 vs space-to-depth) and, on TPU,
    batch 64 vs 128 — the two live hypotheses for the 0.272 MFU
    (docs/ResNetMFU.md). Headline = the best variant; per-variant rows
    ride along so the A/B is captured the moment a chip is reachable."""
    import numpy as np
    import optax

    from tf_yarn_tpu.benchmark import measure_throughput
    from tf_yarn_tpu.models import common, resnet

    size = 224 if tpu else 32
    rng = np.random.RandomState(0)

    def run_one(spec):
        from tf_yarn_tpu.benchmark import kernel_bwd_env

        stem, batch, fused, gn_bwd = spec
        config = (
            resnet.ResNetConfig.resnet50(stem=stem, fused_norms=fused)
            if tpu
            else resnet.ResNetConfig.tiny(stem=stem, fused_norms=fused))
        model = resnet.ResNet(config)
        with kernel_bwd_env(gn_bwd):
            return measure_throughput(
                model,
                common.classification_loss,
                optax.sgd(0.1, momentum=0.9),
                {
                    "x": rng.randn(batch, size, size, 3).astype(np.float32),
                    "y": rng.randint(
                        0, config.num_classes, batch).astype(np.int32),
                },
                steps=10 if tpu else 5,
            )

    # The winning s2d+fused config splits fwd-only vs fwd+bwd GroupNorm
    # kernels (VERDICT r4 item 5's A/B, resnet edition).
    variants = (
        [("conv_b64", ("conv", 64, False, False)),
         ("s2d_b64", ("space_to_depth", 64, False, False)),
         ("s2d_b128", ("space_to_depth", 128, False, False)),
         ("s2d_fused_gn_b128", ("space_to_depth", 128, True, False)),
         ("s2d_fused_gn_bwd_b128", ("space_to_depth", 128, True, True))]
        if tpu else [("conv", ("conv", 8, False, False))]
    )
    return _best_of_variants(variants, run_one)


def bench_vit_base(tpu: bool):
    """ViT-B/16 on 224px images — encoder-stack vision throughput
    (transformer-native counterpart of the resnet50 config). On TPU the
    fused pallas LayerNorm rides as an A/B variant."""
    import numpy as np
    import optax

    from tf_yarn_tpu.benchmark import measure_throughput
    from tf_yarn_tpu.models import common, vit

    batch = 128 if tpu else 8
    rng = np.random.RandomState(0)

    def run_one(fused):
        config = (vit.ViTConfig.base16(fused_norms=fused) if tpu
                  else vit.ViTConfig.tiny(fused_norms=fused))
        size = config.image_size
        model = vit.ViT(config)
        return measure_throughput(
            model,
            common.classification_loss,
            optax.adamw(3e-4),
            {
                "x": rng.randn(batch, size, size, 3).astype(np.float32),
                "y": rng.randint(
                    0, config.num_classes, batch).astype(np.int32),
            },
            steps=10 if tpu else 5,
        )

    variants = ([("base", False), ("fused_ln", True)] if tpu
                else [("base", False)])
    return _best_of_variants(variants, run_one)


def bench_llama_lora(tpu: bool):
    import numpy as np

    from tf_yarn_tpu.benchmark import measure_throughput
    from tf_yarn_tpu.models import common
    from tf_yarn_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        make_lora_optimizer,
    )

    if tpu:
        # Largest decoder that fits one v5e chip comfortably for a bench.
        # flash attention is what makes it fit: xla attention's saved
        # f32 [B,H,S,S] logits alone exceed HBM at this depth.
        config = TransformerConfig(
            vocab_size=32000, d_model=2048, n_layers=16, n_heads=16,
            n_kv_heads=8, d_ff=5632, max_seq_len=2048, lora_rank=16,
            remat=False, attention_impl="flash", fused_norms=True,
            scan_layers=False,
        )
        batch, seq = 4, 1024
    else:
        config = TransformerConfig.tiny(lora_rank=4)
        batch, seq = 8, 32
    rng = np.random.RandomState(0)
    model = Transformer(config)
    return measure_throughput(
        model,
        common.lm_loss,
        make_lora_optimizer(1e-4),
        {"tokens": rng.randint(0, config.vocab_size, (batch, seq)).astype(np.int32)},
        init_fn=lambda r, b: model.init(r, b["tokens"]),
        steps=10 if tpu else 5,
    )


def bench_dlrm_clicks(tpu: bool):
    """Deep CTR on the Criteo-clicks shape: 26 embedding tables stacked
    into one fsdp-sharded param + MXU pairwise interaction."""
    import numpy as np
    import optax

    from tf_yarn_tpu.benchmark import measure_throughput
    from tf_yarn_tpu.models.dlrm import DLRM, DLRMConfig, dlrm_loss

    config = DLRMConfig.criteo() if tpu else DLRMConfig.tiny()
    batch = 4096 if tpu else 256
    rng = np.random.RandomState(0)
    sizes = np.asarray(config.table_sizes)
    model = DLRM(config)
    return measure_throughput(
        model,
        dlrm_loss,
        optax.adagrad(1e-3),
        {
            "cat": rng.randint(0, sizes, (batch, len(sizes))).astype(np.int32),
            "dense": rng.randn(batch, config.n_dense).astype(np.float32),
            "y": rng.randint(0, 2, batch).astype(np.int32),
        },
        init_fn=lambda r, b: model.init(r, b["cat"], b["dense"]),
        steps=10 if tpu else 5,
    )


def bench_long_context(tpu: bool):
    """Long-sequence training on one chip: flash attention + chunked-vocab
    loss are what make S=8192 fit (xla attention's f32 logits alone would
    be 32 GiB here). Reported as tokens/sec/chip.

    On TPU this is an A/B matrix targeting the 0.327 MFU hypotheses
    ranked in docs/LongContext.md: `headdim128` (d_head 64 half-fills
    the 128-wide MXU on the ~30%-of-FLOPs attention contractions),
    `fullloss` (the chunked-vocab loss recomputes the head per chunk),
    plus an attention-only block-size microbench (grid overhead vs VMEM
    pressure). The headline stays the base config so cross-round
    comparisons hold."""
    import numpy as np
    import optax

    from tf_yarn_tpu.benchmark import measure_throughput
    from tf_yarn_tpu.models import common
    from tf_yarn_tpu.models.transformer import Transformer, TransformerConfig

    base_cfg = dict(
        vocab_size=32000, d_model=1024, n_layers=8, n_heads=16,
        n_kv_heads=8, d_ff=4096, max_seq_len=8192, remat=False,
        attention_impl="flash", fused_norms=True, scan_layers=False,
    )
    if tpu:
        batch, seq, steps = 1, 8192, 10
    else:
        batch, seq, steps = 2, 64, 3
    rng = np.random.RandomState(0)

    def run_one(overrides, loss_fn):
        config = (TransformerConfig(**{**base_cfg, **overrides}) if tpu
                  else TransformerConfig.tiny(attention_impl="flash"))
        return measure_throughput(
            Transformer(config),
            loss_fn,
            optax.adamw(1e-4),
            {"tokens": rng.randint(
                0, config.vocab_size, (batch, seq)).astype(np.int32)},
            steps=steps,
        )

    stats = run_one({}, common.lm_loss_chunked)
    stats["tokens_per_sec_per_chip"] = stats["samples_per_sec_per_chip"] * seq
    if not tpu:
        return stats

    variants = [
        # Hypothesis 3 (docs/LongContext.md): chunk recompute cost.
        ("fullloss", {}, common.lm_loss),
        # Hypothesis 1: MXU fill — same d_model, 128-deep head dim.
        ("headdim128", {"n_heads": 8, "n_kv_heads": 8},
         common.lm_loss_chunked),
    ]
    rows = {}
    for name, overrides, loss_fn in variants:
        try:
            v = run_one(overrides, loss_fn)
            rows[name] = {
                "tokens_per_sec_per_chip":
                    round(v["samples_per_sec_per_chip"] * seq, 1),
                "step_time_ms": round(v["step_time_ms"], 2),
                "mfu": round(v["mfu"], 4) if "mfu" in v else None,
            }
        except Exception as exc:  # noqa: BLE001 - record, keep benching
            rows[name] = {"error": f"{type(exc).__name__}: {exc}"}
    stats["variants"] = rows
    stats["attn_microbench"] = _flash_block_microbench(seq)
    return stats


def _flash_block_microbench(seq: int):
    """Attention-only fwd+bwd at S=seq across flash block sizes — the
    direct probe of the flash-grid hypothesis (one number per block
    config, TFLOP/s on the 4·S²·d·0.5 causal attention FLOPs)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_yarn_tpu.ops.flash_attention import flash_attention

    b, h, d_head = 1, 16, 64
    rng = np.random.RandomState(0)
    qkv = [
        jnp.asarray(rng.randn(b, h, seq, d_head).astype(np.float32),
                    jnp.bfloat16)
        for _ in range(3)
    ]
    flops = 3 * (4 * seq * seq * d_head * h * b) // 2  # train, causal
    rows = {}
    for block in (256, 512, 1024):
        @jax.jit
        def step(q, k, v, block=block):
            def loss(q):
                out = flash_attention(
                    q, k, v, causal=True, block_q=block, block_k=block)
                return (out.astype(jnp.float32) ** 2).sum()
            return jax.grad(loss)(q)

        try:
            g = step(*qkv)
            float(jnp.sum(g.astype(jnp.float32)))  # sync (relay-safe)
            t0 = time.perf_counter()
            for _ in range(3):
                g = step(*qkv)
            float(jnp.sum(g.astype(jnp.float32)))
            dt = (time.perf_counter() - t0) / 3
            rows[f"block{block}"] = {
                "ms": round(dt * 1e3, 2),
                "tflops": round(flops / dt / 1e12, 1),
            }
        except Exception as exc:  # noqa: BLE001
            rows[f"block{block}"] = {"error": f"{type(exc).__name__}: {exc}"}
    return rows


def _spec_decode_ab(tpu: bool, ks=(2, 4)):
    """Exact vs speculative decoding A/B on ONE seeded repeated-structure
    trace: the same prompts (each tiling a short motif — the shape
    n-gram/prompt-lookup drafting exists for: templated/structured
    traffic) decode through the SAME engine with spec_k = 0 (exact) and
    spec_k in `ks`, reporting end-to-end tokens/s and accepted-tokens
    per emitting step. Streams are asserted identical across rows — the
    speculative path is a latency lever, not a different sampler."""
    import time

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_yarn_tpu.models.decode_engine import DecodeEngine
    from tf_yarn_tpu.models.transformer import Transformer, TransformerConfig
    from tf_yarn_tpu.parallel.mesh import select_devices
    from tf_yarn_tpu.serving import SamplingParams, SlotScheduler

    select_devices()
    if tpu:
        config = TransformerConfig(
            vocab_size=32000, d_model=1024, n_layers=8, n_heads=16,
            n_kv_heads=8, d_ff=4096, max_seq_len=2048, remat=False,
            scan_layers=False,
        )
        n_requests, max_slots, prompt_len, max_new = 16, 8, 64, 128
    else:
        config = TransformerConfig.tiny(scan_layers=False, max_seq_len=128)
        n_requests, max_slots, prompt_len, max_new = 6, 4, 12, 32
    model = Transformer(config)
    rng = np.random.RandomState(7)
    params = nn.meta.unbox(
        model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, prompt_len), jnp.int32)
        )
    )
    engine = DecodeEngine(model)
    # Repeated structure: each prompt tiles a 3-token motif, so the
    # greedy continuation is (near-)periodic and prompt-lookup drafts
    # land. One seeded trace shared by every row.
    prompts = []
    for _ in range(n_requests):
        motif = rng.randint(0, config.vocab_size, (3,))
        prompts.append(
            np.tile(motif, -(-prompt_len // 3))[:prompt_len].tolist()
        )

    def run_row(spec_k):
        scheduler = SlotScheduler(
            engine, params, max_slots=max_slots,
            queue_capacity=n_requests, spec_k=spec_k,
        )
        scheduler.start()
        try:
            # Warmup: compile prefill + the row's step program outside
            # the timed window.
            scheduler.submit(
                prompts[0], SamplingParams(max_new_tokens=2)
            ).result(timeout=600)
            t0 = time.perf_counter()
            responses = [
                scheduler.submit(p, SamplingParams(max_new_tokens=max_new))
                for p in prompts
            ]
            streams = [r.result(timeout=600) for r in responses]
            wall = time.perf_counter() - t0
            # Accepted-tokens per emitting step, from the tick trace
            # (exact rows have no `accepted` entries: by definition 1).
            accepted = [
                n
                for entry in scheduler.trace
                for n in entry.get("accepted", {}).values()
            ]
            per_step = (
                round(sum(accepted) / len(accepted), 3) if accepted else 1.0
            )
            stats = scheduler.stats()
            return streams, {
                "spec_k": spec_k,
                "tokens_per_sec": round(
                    n_requests * max_new / wall, 2
                ),
                "wall_s": round(wall, 3),
                "accepted_tokens_per_step": per_step,
                "accept_rate": (stats.get("spec") or {}).get("accept_rate"),
            }
        finally:
            scheduler.close()

    exact_streams, exact_row = run_row(0)
    rows = {"exact": exact_row}
    for k in ks:
        streams, row = run_row(k)
        row["streams_match_exact"] = streams == exact_streams
        row["speedup_vs_exact"] = (
            round(row["tokens_per_sec"] / exact_row["tokens_per_sec"], 3)
            if exact_row["tokens_per_sec"] else None
        )
        rows[f"k{k}"] = row
    return {
        "requests": n_requests,
        "max_slots": max_slots,
        "prompt_len": prompt_len,
        "max_new_tokens": max_new,
        "rows": rows,
    }


def _tp_serve_ab(tpu: bool, tp=2):
    """Tensor-parallel decode A/B on ONE seeded Poisson trace: the same
    requests serve through a tp=1 engine and a tp=`tp` engine (weights
    placed by the logical rules, paged KV pool sharded by kv-heads),
    reporting tokens/s and the per-DEVICE resident KV bytes. Streams
    are asserted identical across rows — sharding is a placement
    change, not a sampler change. On the CPU rig the tp "devices" are
    threads contending on one socket, so the SPEED ratio there is NOT
    evidence; the per-device HBM accounting is (the claim tp exists
    for: a model bigger than one chip serving online)."""
    import time

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_yarn_tpu import inference
    from tf_yarn_tpu.models.decode_engine import DecodeEngine
    from tf_yarn_tpu.models.transformer import Transformer, TransformerConfig
    from tf_yarn_tpu.parallel.mesh import MeshSpec, build_mesh, select_devices

    from tf_yarn_tpu.serving import SamplingParams, SlotScheduler

    devices = select_devices()
    if len(devices) < tp:
        return {
            "skipped": (
                f"needs {tp} devices, have {len(devices)} — set "
                f"TPU_YARN_VIRTUAL_DEVICES={tp} (or run on a slice) "
                "before jax initializes"
            ),
        }
    if tpu:
        config = TransformerConfig(
            vocab_size=32000, d_model=1024, n_layers=8, n_heads=16,
            n_kv_heads=8, d_ff=4096, max_seq_len=2048, remat=False,
            scan_layers=False,
        )
        n_requests, max_slots, prompt_len, max_new = 16, 8, 64, 128
        block_size = 16
    else:
        # f32 on the CPU rig: a random-init bf16 model's logits sit on
        # a ~1e-3 grid, so greedy near-ties flip under ANY reduction
        # regrouping (sharded or not — the paged-vs-legacy tests pin
        # f32 for the same reason); f32 keeps the match flag meaningful.
        config = TransformerConfig.tiny(
            scan_layers=False, max_seq_len=128, dtype=jnp.float32,
        )
        n_requests, max_slots, prompt_len, max_new = 6, 4, 12, 24
        block_size = 8
    model = Transformer(config)
    rng = np.random.RandomState(11)
    params = nn.meta.unbox(
        model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, prompt_len), jnp.int32)
        )
    )
    prompts = [
        rng.randint(0, config.vocab_size, (prompt_len,)).tolist()
        for _ in range(n_requests)
    ]
    worst_tokens = prompt_len + max_new - 1
    num_blocks = max_slots * (-(-worst_tokens // block_size)) + 1

    def run_row(degree):
        mesh = None
        row_params = params
        if degree > 1:
            mesh = build_mesh(MeshSpec(tp=degree), devices[:degree])
            row_params = inference.shard_restored_params(
                model, params, mesh
            )
        engine = DecodeEngine(model, mesh=mesh)
        scheduler = SlotScheduler(
            engine, row_params, max_slots=max_slots,
            queue_capacity=n_requests, kv_layout="paged",
            block_size=block_size, num_blocks=num_blocks,
        )
        scheduler.start()
        try:
            scheduler.submit(
                prompts[0], SamplingParams(max_new_tokens=2)
            ).result(timeout=600)
            t0 = time.perf_counter()
            responses = [
                scheduler.submit(p, SamplingParams(max_new_tokens=max_new))
                for p in prompts
            ]
            streams = [r.result(timeout=600) for r in responses]
            wall = time.perf_counter() - t0
            stats = scheduler.stats()
            return streams, {
                "tp": degree,
                "tokens_per_sec": round(n_requests * max_new / wall, 2),
                "wall_s": round(wall, 3),
                "kv_hbm_bytes": stats["kv_cache_hbm_bytes"],
                "kv_hbm_bytes_per_device": stats[
                    "kv_cache_hbm_bytes_per_device"
                ],
            }
        finally:
            scheduler.close()

    base_streams, base_row = run_row(1)
    tp_streams, tp_row = run_row(tp)
    tp_row["streams_match_tp1"] = tp_streams == base_streams
    return {
        "requests": n_requests,
        "max_slots": max_slots,
        "prompt_len": prompt_len,
        "max_new_tokens": max_new,
        "rows": {"tp1": base_row, f"tp{tp}": tp_row},
        "kv_per_device_ratio": (
            round(
                tp_row["kv_hbm_bytes_per_device"]
                / base_row["kv_hbm_bytes_per_device"], 3
            )
            if base_row["kv_hbm_bytes_per_device"] else None
        ),
        "note": (
            "CPU-rig tokens/s ratios are socket contention, not "
            "evidence; the per-device KV accounting is the claim"
        ),
    }


def _chunked_serve_ab(tpu: bool):
    """Blocking vs chunked admission prefill A/B on ONE seeded Poisson
    trace with a BIMODAL prompt mix — short decode-bound requests
    streaming tokens while occasional long prompts (2k tokens on TPU
    shapes) arrive. Blocking admission runs the whole prompt's prefill
    inside the tick, so every resident decode stream stalls for it;
    chunked admission replays the prompt in fixed windows under a
    per-tick budget, so decode slots advance every tick. The rows
    report TTFT p95 AND inter-token-latency p95 (the pooled per-request
    gap series — the long-prompt stall shows up as ITL tail, which is
    the metric chunking exists to flatten), and the chunked row asserts
    its streams bit-identical to blocking (chunking is a scheduling
    change, not a sampler change)."""
    import time

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_yarn_tpu.models.decode_engine import DecodeEngine
    from tf_yarn_tpu.models.transformer import Transformer, TransformerConfig
    from tf_yarn_tpu.parallel.mesh import select_devices
    from tf_yarn_tpu.serving import SamplingParams, SlotScheduler

    select_devices()
    if tpu:
        config = TransformerConfig(
            vocab_size=32000, d_model=1024, n_layers=8, n_heads=16,
            n_kv_heads=8, d_ff=4096, max_seq_len=2560, remat=False,
            scan_layers=False,
        )
        n_short, n_long, mean_gap_s = 24, 4, 0.02
        short_len, short_new = 32, 192
        long_len, long_new = 2048, 16
        block_size, max_slots = 16, 8
        chunk, budget = 256, 256
    else:
        # f32 on the CPU rig: chunked replays the prompt through the
        # windowed program instead of the prefill program, so bf16
        # greedy near-ties could flip on reduction regrouping alone
        # (same reason _tp_serve_ab pins f32) — f32 keeps the
        # streams_match_blocking flag meaningful.
        config = TransformerConfig.tiny(
            scan_layers=False, max_seq_len=128, dtype=jnp.float32,
        )
        n_short, n_long, mean_gap_s = 8, 2, 0.005
        short_len, short_new = 6, 16
        long_len, long_new = 48, 4
        block_size, max_slots = 8, 4
        chunk, budget = 8, 8
    model = Transformer(config)
    rng = np.random.RandomState(13)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    )
    engine = DecodeEngine(model)

    # One seeded Poisson trace, bimodal: mostly short decode-bound
    # requests with long prompts salted through the middle of the run
    # (a long prompt arriving while decode streams are live is the
    # scenario under test).
    n_requests = n_short + n_long
    arrivals = np.cumsum(rng.exponential(mean_gap_s, n_requests))
    long_at = set(
        rng.choice(np.arange(2, n_requests), n_long, replace=False).tolist()
    )
    requests = []
    for i in range(n_requests):
        length, max_new = (
            (long_len, long_new) if i in long_at else (short_len, short_new)
        )
        requests.append((
            float(arrivals[i]),
            rng.randint(0, config.vocab_size, (length,)).tolist(),
            max_new,
        ))
    total_tokens = sum(m for _, _, m in requests)
    worst_tokens = long_len + long_new - 1
    num_blocks = max_slots * (-(-worst_tokens // block_size)) + 1

    def run_row(chunked: bool):
        kwargs = dict(
            kv_layout="paged", block_size=block_size, num_blocks=num_blocks,
        )
        if chunked:
            kwargs.update(
                prefill_chunk=chunk, prefill_budget_per_tick=budget,
            )
        scheduler = SlotScheduler(
            engine, params, max_slots=max_slots,
            queue_capacity=n_requests, **kwargs,
        )
        scheduler.start()
        try:
            # Warmup: compile both prompt shapes' admission path + the
            # row's step program outside the timed window.
            for length in (short_len, long_len):
                scheduler.submit(
                    [1] * length, SamplingParams(max_new_tokens=2)
                ).result(timeout=600)
            t0 = time.perf_counter()
            responses = []
            for offset, prompt, max_new in requests:
                lag = t0 + offset - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                responses.append((scheduler.submit(
                    prompt, SamplingParams(max_new_tokens=max_new)
                ), offset))
            streams = [r.result(timeout=600) for r, _ in responses]
            wall = time.perf_counter() - t0
            # TTFT against the trace's arrival time; ITL pooled over
            # every request's consecutive-arrival gaps.
            ttfts = [
                (response.first_token_at - t0) - offset
                for response, offset in responses
            ]
            gaps = [
                gap
                for response, _ in responses
                for gap in response.inter_token_gaps_s()
            ]
            stats = scheduler.stats()
            return streams, {
                "prefill_chunk": stats["prefill_chunk"],
                "prefill_budget_per_tick": stats["prefill_budget_per_tick"],
                "tokens_per_sec": round(total_tokens / wall, 2),
                "wall_s": round(wall, 3),
                "ttft_p95_ms": round(
                    1000 * float(np.percentile(ttfts, 95)), 2),
                "itl_p95_ms": round(
                    1000 * float(np.percentile(gaps, 95)), 2),
                "itl_max_ms": round(1000 * max(gaps), 2),
                "prefill_tokens": stats["prefill_tokens"],
                "decode_tokens": stats["decode_tokens"],
            }
        finally:
            scheduler.close()

    blocking_streams, blocking_row = run_row(chunked=False)
    chunked_streams, chunked_row = run_row(chunked=True)
    chunked_row["streams_match_blocking"] = (
        chunked_streams == blocking_streams
    )
    return {
        "requests": n_requests,
        "long_prompts": n_long,
        "max_slots": max_slots,
        "short": {"prompt_len": short_len, "max_new_tokens": short_new},
        "long": {"prompt_len": long_len, "max_new_tokens": long_new},
        "rows": {"blocking": blocking_row, "chunked": chunked_row},
        "itl_p95_ratio": (
            round(chunked_row["itl_p95_ms"] / blocking_row["itl_p95_ms"], 3)
            if blocking_row["itl_p95_ms"] else None
        ),
        "note": (
            "itl_p95/itl_max carry the claim: blocking admission stalls "
            "live decode streams for the long prompt's whole prefill; "
            "chunking bounds the stall at one window per tick. On the "
            "CPU rig the width-W window multiplies per-tick FLOPs on a "
            "serial core, so the ITL ratio there is NOT evidence (same "
            "caveat as the tp rows) — on TPU shapes the window is "
            "memory-bound like the exact step and the ratio is the "
            "claim; streams_match_blocking is evidence on both"
        ),
    }


def _disagg_serve_ab(tpu: bool):
    """Local vs DISAGGREGATED prefill A/B on the same bimodal Poisson
    trace as `_chunked_serve_ab`: short decode-bound requests streaming
    while occasional long prompts arrive. The local row prefills every
    prompt on the decode replica; the offloaded row ships each
    above-threshold prompt to a real PrefillServer over HTTP first
    (PrefillClient two-stage dispatch), so admission's prefix hit skips
    the shipped span. Rows report TTFT p95; the offloaded row asserts
    its streams bit-identical to local (the shipped blocks hold the
    exact KV local prefill would compute) and counts ships/blocks. The
    fp-vs-int8 wire-bytes ratio rides along: the SAME long prompt
    exported through an fp worker vs an int8 worker — int8 blocks ride
    the wire as int8, the ~3x transfer saving."""
    import dataclasses
    import json as json_lib
    import time

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_yarn_tpu.models.decode_engine import DecodeEngine
    from tf_yarn_tpu.models.transformer import Transformer, TransformerConfig
    from tf_yarn_tpu.parallel.mesh import select_devices
    from tf_yarn_tpu.serving import SamplingParams, SlotScheduler
    from tf_yarn_tpu.serving.prefill import (
        PrefillClient,
        PrefillServer,
        PrefillTierConfig,
        PrefillWorker,
    )
    from tf_yarn_tpu.serving.server import encode_block_wire

    select_devices()
    if tpu:
        config = TransformerConfig(
            vocab_size=32000, d_model=1024, n_layers=8, n_heads=16,
            n_kv_heads=8, d_ff=4096, max_seq_len=2560, remat=False,
            scan_layers=False,
        )
        n_short, n_long, mean_gap_s = 24, 4, 0.02
        short_len, short_new = 32, 192
        long_len, long_new = 2048, 16
        block_size, max_slots = 16, 8
        offload_threshold = 256
    else:
        # f32 for the same reason _chunked_serve_ab pins it: the
        # streams_match_local bit must reflect scheduling, not bf16
        # near-tie flips.
        config = TransformerConfig.tiny(
            scan_layers=False, max_seq_len=128, dtype=jnp.float32,
        )
        n_short, n_long, mean_gap_s = 8, 2, 0.005
        short_len, short_new = 6, 16
        long_len, long_new = 48, 4
        block_size, max_slots = 8, 4
        offload_threshold = 16
    model = Transformer(config)
    rng = np.random.RandomState(13)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    )
    engine = DecodeEngine(model)

    # The bimodal trace (same construction as _chunked_serve_ab): long
    # prompts salted through the middle of a short-request stream.
    n_requests = n_short + n_long
    arrivals = np.cumsum(rng.exponential(mean_gap_s, n_requests))
    long_at = set(
        rng.choice(np.arange(2, n_requests), n_long, replace=False).tolist()
    )
    requests = []
    for i in range(n_requests):
        length, max_new = (
            (long_len, long_new) if i in long_at else (short_len, short_new)
        )
        requests.append((
            float(arrivals[i]),
            rng.randint(0, config.vocab_size, (length,)).tolist(),
            max_new,
        ))
    worst_tokens = long_len + long_new - 1
    # Room for active slots AND the imported prefix entries the shipped
    # long prompts land as (they stay evictable but count while hot).
    num_blocks = (
        max_slots * (-(-worst_tokens // block_size))
        + n_long * (-(-long_len // block_size)) + 1
    )

    def run_row(client_factory=None):
        scheduler = SlotScheduler(
            engine, params, max_slots=max_slots,
            queue_capacity=n_requests, kv_layout="paged",
            block_size=block_size, num_blocks=num_blocks,
        )
        client = client_factory(scheduler) if client_factory else None
        scheduler.start()
        try:
            for length in (short_len, long_len):
                warm = [1] * length
                if client is not None:
                    client.maybe_ship(warm)
                scheduler.submit(
                    warm, SamplingParams(max_new_tokens=2)
                ).result(timeout=600)
            t0 = time.perf_counter()
            responses = []
            for offset, prompt, max_new in requests:
                lag = t0 + offset - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                if client is not None:
                    # The server-side hook: pull KV blocks from the
                    # prefill tier BEFORE submitting.
                    client.maybe_ship(prompt)
                responses.append((scheduler.submit(
                    prompt, SamplingParams(max_new_tokens=max_new)
                ), offset))
            streams = [r.result(timeout=600) for r, _ in responses]
            wall = time.perf_counter() - t0
            ttfts = [
                (response.first_token_at - t0) - offset
                for response, offset in responses
            ]
            stats = scheduler.stats()
            row = {
                "wall_s": round(wall, 3),
                "ttft_p95_ms": round(
                    1000 * float(np.percentile(ttfts, 95)), 2),
                "prefill_tokens": stats["prefill_tokens"],
                "prefix_cache_hit_rate": (
                    stats.get("prefix_cache", {}).get("hit_rate")
                ),
            }
            if client is not None:
                row.update(client.stats())
            return streams, row
        finally:
            scheduler.close()

    local_streams, local_row = run_row()

    worker = PrefillWorker(
        engine, params, block_size=block_size,
        num_blocks=num_blocks,
    )
    server = PrefillServer(worker)
    server.start()
    try:
        offloaded_streams, offloaded_row = run_row(
            lambda scheduler: PrefillClient(
                PrefillTierConfig(
                    offload_threshold=offload_threshold,
                    endpoint=server.endpoint,
                ),
                scheduler, block_size=block_size,
            )
        )
        offloaded_row["streams_match_local"] = (
            offloaded_streams == local_streams
        )

        # fp-vs-int8 wire size on ONE long prompt: an int8 worker's
        # quantized blocks ride the wire as int8.
        long_prompt = next(
            prompt for _, prompt, _ in requests if len(prompt) == long_len
        )
        fp_bytes = len(json_lib.dumps(encode_block_wire(
            worker.prefill_prompt(long_prompt)
        )))
        int8_model = Transformer(dataclasses.replace(
            config, kv_cache_dtype="int8"
        ))
        int8_worker = PrefillWorker(
            DecodeEngine(int8_model), params, block_size=block_size,
            num_blocks=num_blocks,
        )
        int8_bytes = len(json_lib.dumps(encode_block_wire(
            int8_worker.prefill_prompt(long_prompt)
        )))
    finally:
        server.stop()

    return {
        "requests": n_requests,
        "long_prompts": n_long,
        "max_slots": max_slots,
        "offload_threshold": offload_threshold,
        "short": {"prompt_len": short_len, "max_new_tokens": short_new},
        "long": {"prompt_len": long_len, "max_new_tokens": long_new},
        "rows": {"local": local_row, "offloaded": offloaded_row},
        "ttft_p95_ratio": (
            round(
                offloaded_row["ttft_p95_ms"] / local_row["ttft_p95_ms"], 3
            )
            if local_row["ttft_p95_ms"] else None
        ),
        "wire_bytes_fp_over_int8": (
            round(fp_bytes / int8_bytes, 2) if int8_bytes else None
        ),
        "note": (
            "On the CPU rig both tiers share one socket, so the "
            "offloaded row pays the long prefill AND the hop serially — "
            "its TTFT ratio is scheduling evidence only, not the claim; "
            "on real disaggregated hardware the prefill burst leaves "
            "the decode replica entirely. streams_match_local and the "
            "int8 wire ratio are evidence on both rigs"
        ),
    }


def _overload_serve_ab(tpu: bool):
    """Hold-until-free vs suspend-to-host A/B on ONE seeded Poisson
    OVERLOAD trace: batch-tier streams saturate a device pool sized for
    two of them (working set ~= 3x the pool), then interactive-tier
    requests arrive mid-run. Hold-until-free (kv_host_blocks=0) parks
    the interactive arrivals in the queue until a batch stream retires;
    suspend-to-host (kv_host_blocks = 2x the device pool) swaps the
    youngest batch stream's KV blocks to host RAM and admits the
    interactive request in the same tick, resuming the parked stream —
    bit-identically — once the pool frees. Both tiers get the SAME
    block footprint (prompt + budget spanning equal whole blocks) so
    peak_streams isolates the scheduling policy: the hold row tops out
    at pool/footprint streams, the suspend row carries pool/footprint
    active PLUS the suspended tier on top. interactive_ttft_p95 is the
    SLO the displacement buys; streams_match_hold asserts suspension is
    a scheduling change, not a sampler change (greedy f32 on the CPU
    rig for exactly the reason _chunked_serve_ab pins f32)."""
    import time

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_yarn_tpu.models.decode_engine import DecodeEngine
    from tf_yarn_tpu.models.transformer import Transformer, TransformerConfig
    from tf_yarn_tpu.parallel.mesh import select_devices
    from tf_yarn_tpu.serving import SamplingParams, SlotScheduler

    select_devices()
    if tpu:
        config = TransformerConfig(
            vocab_size=32000, d_model=1024, n_layers=8, n_heads=16,
            n_kv_heads=8, d_ff=4096, max_seq_len=512, remat=False,
            scan_layers=False,
        )
        n_batch, n_inter = 8, 6
        batch_len, batch_new = 256, 128     # ceil(383/16) = 24 blocks
        inter_len, inter_new = 128, 256     # same 24-block footprint
        block_size, max_slots = 16, 8
        batch_gap_s, inter_gap_s, inter_at_s = 0.02, 0.05, 0.3
    else:
        config = TransformerConfig.tiny(
            scan_layers=False, max_seq_len=64, dtype=jnp.float32,
        )
        n_batch, n_inter = 6, 4
        batch_len, batch_new = 9, 24        # ceil(32/8) = 4 blocks
        inter_len, inter_new = 5, 28        # same 4-block footprint
        block_size, max_slots = 8, 4
        batch_gap_s, inter_gap_s, inter_at_s = 0.005, 0.04, 0.08
    model = Transformer(config)
    rng = np.random.RandomState(23)
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    )
    footprint = -(-(batch_len + batch_new - 1) // block_size)
    assert footprint == -(-(inter_len + inter_new - 1) // block_size)
    # Device pool: exactly TWO streams' residency. The trace's working
    # set (in-system demand at peak) is ~3x that — the oversubscription
    # regime the host tier exists for.
    num_blocks = 2 * footprint + 1
    host_blocks = 2 * num_blocks  # the 2x-device-pool acceptance point

    batch_arrivals = np.cumsum(rng.exponential(batch_gap_s, n_batch))
    inter_arrivals = inter_at_s + np.cumsum(
        rng.exponential(inter_gap_s, n_inter)
    )
    requests = sorted(
        [
            (
                float(batch_arrivals[i]),
                rng.randint(0, config.vocab_size, (batch_len,)).tolist(),
                batch_new, "batch",
            )
            for i in range(n_batch)
        ] + [
            (
                float(inter_arrivals[i]),
                rng.randint(0, config.vocab_size, (inter_len,)).tolist(),
                inter_new, "interactive",
            )
            for i in range(n_inter)
        ],
        key=lambda r: r[0],
    )
    total_tokens = sum(m for _, _, m, _ in requests)

    def run_row(kv_host_blocks: int):
        engine = DecodeEngine(model)
        scheduler = SlotScheduler(
            engine, params, max_slots=max_slots,
            queue_capacity=len(requests), kv_layout="paged",
            block_size=block_size, num_blocks=num_blocks,
            kv_host_blocks=kv_host_blocks,
        )
        scheduler.start()
        try:
            # Warmup: two batch streams fill the pool, then an
            # interactive arrival displaces one — compiling both prompt
            # buckets, the step program, AND (suspend row) the
            # extract/inject swap programs outside the timed window.
            # TTFT must measure scheduling, not XLA.
            warm = [
                scheduler.submit(
                    [1] * batch_len,
                    SamplingParams(max_new_tokens=batch_new), tier="batch",
                )
                for _ in range(2)
            ]
            warm_deadline = time.monotonic() + 600
            while (scheduler.stats()["active_slots"] < 2
                   and time.monotonic() < warm_deadline):
                time.sleep(0.005)
            warm.append(scheduler.submit(
                [1] * inter_len, SamplingParams(max_new_tokens=inter_new),
                tier="interactive",
            ))
            for response in warm:
                response.result(timeout=600)
            t0 = time.perf_counter()
            responses = []
            for offset, prompt, max_new, tier in requests:
                lag = t0 + offset - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                responses.append((scheduler.submit(
                    prompt, SamplingParams(max_new_tokens=max_new),
                    tier=tier,
                ), offset, tier))
            streams = [r.result(timeout=600) for r, _, _ in responses]
            wall = time.perf_counter() - t0
            inter_ttfts = [
                (response.first_token_at - t0) - offset
                for response, offset, tier in responses
                if tier == "interactive"
            ]
            stats = scheduler.stats()
            swap = stats.get("swap", {})
            return streams, {
                "kv_host_blocks": kv_host_blocks,
                "goodput_tokens_per_sec": round(total_tokens / wall, 2),
                "wall_s": round(wall, 3),
                "interactive_ttft_p95_ms": round(
                    1000 * float(np.percentile(inter_ttfts, 95)), 2),
                "peak_streams": stats["peak_streams"],
                "suspends": swap.get("suspends", 0),
                "resumes": swap.get("resumes", 0),
                "swap_out_blocks": swap.get("swap_out_blocks", 0),
                "swap_in_blocks": swap.get("swap_in_blocks", 0),
            }
        finally:
            scheduler.close()

    hold_streams, hold_row = run_row(kv_host_blocks=0)
    suspend_streams, suspend_row = run_row(kv_host_blocks=host_blocks)
    suspend_row["streams_match_hold"] = suspend_streams == hold_streams
    return {
        "requests": len(requests),
        "interactive_requests": n_inter,
        "max_slots": max_slots,
        "block_size": block_size,
        "device_num_blocks": num_blocks,
        "blocks_per_request": footprint,
        "batch": {"prompt_len": batch_len, "max_new_tokens": batch_new},
        "interactive": {
            "prompt_len": inter_len, "max_new_tokens": inter_new,
        },
        "rows": {"hold": hold_row, "suspend": suspend_row},
        "peak_streams_ratio": (
            round(
                suspend_row["peak_streams"] / hold_row["peak_streams"], 3
            ) if hold_row["peak_streams"] else None
        ),
        "interactive_ttft_p95_ratio": (
            round(
                suspend_row["interactive_ttft_p95_ms"]
                / hold_row["interactive_ttft_p95_ms"], 3
            ) if hold_row["interactive_ttft_p95_ms"] else None
        ),
        "note": (
            "peak_streams_ratio and interactive_ttft_p95_ratio carry "
            "the claim: with host blocks at 2x the device pool the "
            "suspend row holds the displaced batch tier IN the system "
            "(peak_streams ~= 2x hold) while interactive TTFT drops to "
            "one displacement tick instead of one batch stream's "
            "remaining decode; streams_match_hold is the bit-identity "
            "evidence. CPU-rig wall/goodput numbers are NOT speed "
            "evidence (serial-core arithmetic, same caveat as the tp "
            "and chunked rows) — the stream counts, swap counters, and "
            "TTFT ordering are the scheduling evidence"
        ),
    }


def bench_decode(tpu: bool, spec: bool = False):
    """Autoregressive decode throughput (tokens/sec), bf16 vs int8 KV
    cache. Decode steps are scanned inside ONE jitted program — per-step
    host dispatch (~5ms through a relay) would otherwise dominate the
    ~ms-scale decode step and measure the wrong thing.

    The `engine` vs `percall_jit` pair A/Bs the serving path itself:
    `DecodeEngine` (compile cached across calls, on-device EOS loop,
    donated cache) against the legacy `generate_legacy` host loop (fresh
    jitted step closure per call + one host sync per token). Both time a
    SECOND call end-to-end — exactly what a warm server pays per batch —
    so the engine's cached compile and the legacy path's per-call
    retrace are both visible in the number."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_yarn_tpu.models.decode_engine import DecodeEngine
    from tf_yarn_tpu.models.generate import generate_legacy
    from tf_yarn_tpu.models.transformer import Transformer, TransformerConfig
    from tf_yarn_tpu.parallel.mesh import select_devices

    # Narrows the backend per TPU_YARN_PLATFORM (on the CPU rig the
    # default backend would dial the TPU relay and hang).
    select_devices()

    results = {}
    for cache_dtype in ("bf16", "int8"):
        if tpu:
            config = TransformerConfig(
                vocab_size=32000, d_model=1024, n_layers=8, n_heads=16,
                n_kv_heads=8, d_ff=4096, max_seq_len=2048, remat=False,
                scan_layers=False, kv_cache_dtype=cache_dtype,
            )
            batch, prefill_len, decode_tokens = 8, 128, 256
        else:
            config = TransformerConfig.tiny(kv_cache_dtype=cache_dtype,
                                            scan_layers=False)
            batch, prefill_len, decode_tokens = 2, 8, 16
        model = Transformer(config)
        rng = np.random.RandomState(0)
        prompt = jnp.asarray(
            rng.randint(0, config.vocab_size, (batch, prefill_len)), jnp.int32
        )
        params = jax.jit(model.init)(jax.random.PRNGKey(0), prompt)

        def prefill(params, prompt):
            logits, state = model.apply(
                params, prompt, decode=True, mutable=["cache"]
            )
            return state["cache"], jnp.argmax(
                logits[:, -1], axis=-1
            ).astype(jnp.int32)

        def decode_n(params, cache, token):
            def body(carry, _):
                cache, token = carry
                logits, state = model.apply(
                    {**params, "cache": cache}, token[:, None], decode=True,
                    mutable=["cache"],
                )
                return (
                    state["cache"],
                    jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32),
                ), ()
            (cache, token), _ = jax.lax.scan(
                body, (cache, token), None, length=decode_tokens
            )
            return token

        cache, token = jax.jit(prefill)(params, prompt)
        run = jax.jit(decode_n).lower(params, cache, token).compile()
        last = run(params, cache, token)  # warmup
        int(jax.device_get(last)[0])
        t0 = time.time()
        last = run(params, cache, token)
        int(jax.device_get(last)[0])
        elapsed = time.time() - t0
        results[f"decode_tokens_per_sec_{cache_dtype}"] = round(
            batch * decode_tokens / elapsed, 2
        )
        results[f"decode_ms_per_step_{cache_dtype}"] = round(
            1000 * elapsed / decode_tokens, 3
        )

        def _timed_call(fn):
            # Warm call compiles (engine) / traces (per-call jit); sync
            # it so no async tail leaks into the timed window.
            int(jax.device_get(fn())[0, -1])
            t0 = time.time()
            out = fn()
            int(jax.device_get(out)[0, -1])  # sync (relay-safe)
            return batch * decode_tokens / (time.time() - t0)

        try:
            engine = DecodeEngine(model)
            results[f"engine_tokens_per_sec_{cache_dtype}"] = round(
                _timed_call(lambda: engine.generate(
                    params, prompt, decode_tokens, temperature=0.0)), 2
            )
            results[f"engine_decode_compiles_{cache_dtype}"] = (
                engine.stats["decode_compiles"]
            )
            results[f"percall_jit_tokens_per_sec_{cache_dtype}"] = round(
                _timed_call(lambda: generate_legacy(
                    model, params, prompt, decode_tokens,
                    temperature=0.0)), 2
            )
        except Exception as exc:  # noqa: BLE001 - record, keep benching
            results[f"engine_error_{cache_dtype}"] = (
                f"{type(exc).__name__}: {exc}"[:160]
            )
    out = {
        "batch": batch, "prefill": prefill_len,
        "decode_tokens": decode_tokens, **results,
    }
    if spec:
        # `decode --spec`: the exact-vs-speculative A/B rides along.
        try:
            out["spec"] = _spec_decode_ab(tpu)
        except Exception as exc:  # noqa: BLE001 - record, keep benching
            out["spec"] = {"error": f"{type(exc).__name__}: {exc}"[:160]}
    return out


def bench_serve(tpu: bool, tp: bool = False, chunked: bool = False,
                overload: bool = False, disagg: bool = False):
    """Online-serving A/B matrix under ONE seeded Poisson arrival trace:

    * **policy** — continuous batching (freed slots re-admitted next
      tick) vs static batching (admissions wait for the whole batch to
      drain), same dense grid: the scheduling-policy delta.
    * **KV layout** — dense per-slot caches vs the paged block pool
      (sized BELOW dense-equivalent) vs paged + int8 KV, all continuous:
      the memory-engineering delta. Each layout row reports resident KV
      HBM and slots-per-GB — the concurrency-per-chip lever paged/int8
      exist to multiply — alongside throughput and tail TTFT to show the
      capacity is not bought with latency."""
    import time

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_yarn_tpu.models.decode_engine import DecodeEngine
    from tf_yarn_tpu.models.transformer import Transformer, TransformerConfig
    from tf_yarn_tpu.parallel.mesh import select_devices
    from tf_yarn_tpu.serving import SamplingParams, SlotScheduler

    select_devices()
    if tpu:
        base_cfg = dict(
            vocab_size=32000, d_model=1024, n_layers=8, n_heads=16,
            n_kv_heads=8, d_ff=4096, max_seq_len=2048, remat=False,
            scan_layers=False,
        )
        config = TransformerConfig(**base_cfg)
        n_requests, max_slots, mean_gap_s = 32, 8, 0.02
        prompt_lens, max_new_range = (64, 128, 256), (32, 256)
        block_size = 16
    else:
        base_cfg = dict(scan_layers=False, max_seq_len=64)
        config = TransformerConfig.tiny(**base_cfg)
        n_requests, max_slots, mean_gap_s = 12, 4, 0.005
        prompt_lens, max_new_range = (5, 9, 14), (2, 16)
        block_size = 8
    model = Transformer(config)
    rng = np.random.RandomState(0)
    params = nn.meta.unbox(
        model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, max(prompt_lens)), jnp.int32),
        )
    )

    # One seeded Poisson trace shared by every policy and layout.
    gaps = rng.exponential(mean_gap_s, n_requests)
    arrivals = np.cumsum(gaps)
    requests = [
        (
            float(arrivals[i]),
            rng.randint(0, config.vocab_size,
                        rng.choice(prompt_lens)).tolist(),
            int(rng.randint(*max_new_range)),
        )
        for i in range(n_requests)
    ]
    total_tokens = sum(m for _, _, m in requests)
    # Paged pool sized to the trace's worst-case concurrent residency
    # (every slot holding its longest possible request), NOT to
    # max_slots full contexts — the HBM the dense layout wastes on
    # padding is exactly the gap between these two numbers.
    worst_tokens = max(prompt_lens) + max_new_range[1] - 1
    paged_blocks = max_slots * (-(-worst_tokens // block_size)) + 1

    def run_policy(continuous: bool, run_model=None,
                   scheduler_kwargs=None):
        engine = DecodeEngine(run_model if run_model is not None else model)
        scheduler = SlotScheduler(
            engine, params, max_slots=max_slots,
            queue_capacity=n_requests, **(scheduler_kwargs or {}),
        )
        scheduler.start()
        try:
            # Warmup: compile every prompt bucket's prefill + the step
            # program outside the timed window (a warm server's steady
            # state) — TTFT must measure scheduling, not XLA.
            for length in prompt_lens:
                scheduler.submit(
                    [1] * length, SamplingParams(max_new_tokens=2)
                ).result(timeout=300)
            responses = []
            t0 = time.perf_counter()
            if continuous:
                for offset, prompt, max_new in requests:
                    lag = t0 + offset - time.perf_counter()
                    if lag > 0:
                        time.sleep(lag)
                    responses.append((scheduler.submit(
                        prompt, SamplingParams(max_new_tokens=max_new)
                    ), offset))
                for response, _ in responses:
                    response.result(timeout=600)
            else:
                # Static batching: the next group is submitted only when
                # the previous one fully drained — a freed slot idles.
                for start in range(0, n_requests, max_slots):
                    group = requests[start:start + max_slots]
                    lag = t0 + group[-1][0] - time.perf_counter()
                    if lag > 0:
                        time.sleep(lag)
                    batch = [
                        (scheduler.submit(
                            prompt, SamplingParams(max_new_tokens=max_new)
                        ), offset)
                        for offset, prompt, max_new in group
                    ]
                    for response, _ in batch:
                        response.result(timeout=600)
                    responses.extend(batch)
            wall = time.perf_counter() - t0
            # TTFT measured against the trace's arrival time, not the
            # submit call — static batching's queue wait must count.
            ttfts = sorted(
                (response.first_token_at - t0) - offset
                for response, offset in responses
            )
            stats = scheduler.stats()
            kv_bytes = stats["kv_cache_hbm_bytes"]
            return {
                "tokens_per_sec": round(total_tokens / wall, 2),
                "wall_s": round(wall, 3),
                "ttft_mean_ms": round(
                    1000 * sum(ttfts) / len(ttfts), 2),
                "ttft_p95_ms": round(
                    1000 * ttfts[int(0.95 * (len(ttfts) - 1))], 2),
                "step_compiles": engine.stats["step_compiles"]
                + engine.stats["paged_step_compiles"],
                "kv_hbm_bytes": kv_bytes,
                "slots_per_gb_hbm": round(
                    max_slots / (kv_bytes / 2**30), 2) if kv_bytes else None,
                "prefix_cache_hit_rate": (
                    stats.get("prefix_cache", {}).get("hit_rate")
                ),
            }
        finally:
            scheduler.close()

    continuous = run_policy(continuous=True)
    static = run_policy(continuous=False)
    speedup = (
        round(continuous["tokens_per_sec"] / static["tokens_per_sec"], 3)
        if static["tokens_per_sec"] else None
    )

    # KV-layout A/B (all continuous): dense is the run above; paged
    # shrinks the pool below dense-equivalent; paged_int8 halves the
    # bytes per cached token on top.
    paged_kwargs = dict(
        kv_layout="paged", block_size=block_size, num_blocks=paged_blocks,
    )
    layouts = {"dense": continuous}
    try:
        layouts["paged"] = run_policy(
            continuous=True, scheduler_kwargs=paged_kwargs
        )
    except Exception as exc:  # noqa: BLE001 - record, keep benching
        layouts["paged"] = {"error": f"{type(exc).__name__}: {exc}"[:160]}
    try:
        int8_model = Transformer(
            TransformerConfig(**base_cfg, kv_cache_dtype="int8")
            if tpu else TransformerConfig.tiny(
                **base_cfg, kv_cache_dtype="int8")
        )
        layouts["paged_int8"] = run_policy(
            continuous=True, run_model=int8_model,
            scheduler_kwargs=paged_kwargs,
        )
    except Exception as exc:  # noqa: BLE001
        layouts["paged_int8"] = {
            "error": f"{type(exc).__name__}: {exc}"[:160]
        }
    ratios = {}
    dense_spg = continuous.get("slots_per_gb_hbm")
    for name in ("paged", "paged_int8"):
        spg = layouts[name].get("slots_per_gb_hbm")
        if spg and dense_spg:
            ratios[f"{name}_vs_dense_slots_per_gb"] = round(
                spg / dense_spg, 2
            )
    # Speculative decoding A/B (exact vs k ∈ {2, 4} on one seeded
    # repeated-structure trace): the per-token latency lever riding on
    # the same serving stack.
    try:
        spec = _spec_decode_ab(tpu)
    except Exception as exc:  # noqa: BLE001 - record, keep benching
        spec = {"error": f"{type(exc).__name__}: {exc}"[:160]}
    out = {
        "requests": n_requests,
        "max_slots": max_slots,
        "total_tokens": total_tokens,
        "block_size": block_size,
        "paged_num_blocks": paged_blocks,
        "continuous": continuous,
        "static": static,
        "continuous_vs_static_speedup": speedup,
        "layouts": layouts,
        "spec": spec,
        **ratios,
    }
    if tp:
        # Tensor-parallel A/B (`serve --tp`): tp=1 vs tp=2 on the same
        # seeded trace; the per-device KV accounting is the evidence,
        # CPU-rig speed ratios are not (see _tp_serve_ab).
        try:
            out["tp"] = _tp_serve_ab(tpu)
        except Exception as exc:  # noqa: BLE001 - record, keep benching
            out["tp"] = {"error": f"{type(exc).__name__}: {exc}"[:160]}
    if chunked:
        # Chunked-prefill A/B (`serve --chunked`): blocking vs chunked
        # admission on one bimodal Poisson trace; ITL p95 is the claim.
        try:
            out["chunked"] = _chunked_serve_ab(tpu)
        except Exception as exc:  # noqa: BLE001 - record, keep benching
            out["chunked"] = {"error": f"{type(exc).__name__}: {exc}"[:160]}
    if overload:
        # KV-oversubscription A/B (`serve --overload`): hold-until-free
        # vs suspend-to-host on one seeded overload trace; the
        # peak-streams ratio and interactive TTFT are the claim.
        try:
            out["overload"] = _overload_serve_ab(tpu)
        except Exception as exc:  # noqa: BLE001 - record, keep benching
            out["overload"] = {
                "error": f"{type(exc).__name__}: {exc}"[:160]
            }
    if disagg:
        # Disaggregated-prefill A/B (`serve --disagg`): local vs
        # offloaded prefill on the bimodal trace; streams_match_local
        # and the fp-vs-int8 wire ratio are the claim.
        try:
            out["disagg"] = _disagg_serve_ab(tpu)
        except Exception as exc:  # noqa: BLE001 - record, keep benching
            out["disagg"] = {
                "error": f"{type(exc).__name__}: {exc}"[:160]
            }
    return out


def bench_fleet(tpu: bool, replica_counts=(1, 2, 4), n_requests=None,
                autoscale=False):
    """Fleet mode of the serve bench: aggregate tokens/s and TTFT p95
    vs replica count under the SAME seeded Poisson arrival trace,
    driven end-to-end through the fleet ROUTER (tf_yarn_tpu/fleet/):
    N real serving stacks (scheduler + HTTP frontend) advertise into an
    in-process KV, the replica registry probes them healthy, and every
    request streams through the router's ``/v1/generate`` passthrough —
    TTFT is measured client-side at first token line, so discovery,
    balancing, and the extra hop are all inside the number. The decode
    engine (and its compiled programs) is shared across replicas, so
    the sweep measures the replica axis, not recompilation.

    ``autoscale=True`` (`fleet --autoscale`) switches to the elastic
    A/B instead of the replica sweep: a STATIC 2-replica fleet vs an
    AUTOSCALED one (start 2, max 4, FleetAutoscaler side-car with an
    in-process spawn actuator + real /v1/blocks peer warm start) under
    the SAME seeded Poisson trace with a mid-run rate step, plus one
    injected replica preemption (eject + relaunch on a NEW port, the
    registry re-admit path) in BOTH arms. Reported: per-arm
    SLO-violation rate (client-side TTFT over the threshold), dropped
    in-flight streams (must be 0), and ``streams_match`` — the two
    arms' per-request token sequences compared bit-for-bit (scaling
    must change WHEN tokens arrive, never WHICH). On the CPU rig the
    latency numbers are scheduling evidence only."""
    import threading
    import time

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_yarn_tpu import event, telemetry
    from tf_yarn_tpu.coordination.kv import InProcessKV
    from tf_yarn_tpu.fleet import (
        FleetMonitor,
        ReplicaRegistry,
        RouterServer,
        make_policy,
    )
    from tf_yarn_tpu.models.decode_engine import DecodeEngine
    from tf_yarn_tpu.models.transformer import Transformer, TransformerConfig
    from tf_yarn_tpu.parallel.mesh import select_devices
    from tf_yarn_tpu.serving import SamplingParams, ServingServer, SlotScheduler

    select_devices()
    if tpu:
        config = TransformerConfig(
            vocab_size=32000, d_model=1024, n_layers=8, n_heads=16,
            n_kv_heads=8, d_ff=4096, max_seq_len=2048, remat=False,
            scan_layers=False,
        )
        default_requests, max_slots, mean_gap_s = 32, 8, 0.02
        prompt_lens, max_new_range = (64, 128, 256), (32, 256)
    else:
        config = TransformerConfig.tiny(scan_layers=False, max_seq_len=64)
        default_requests, max_slots, mean_gap_s = 12, 4, 0.005
        prompt_lens, max_new_range = (5, 9, 14), (2, 16)
    model = Transformer(config)
    rng = np.random.RandomState(0)
    params = nn.meta.unbox(
        model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, max(prompt_lens)), jnp.int32),
        )
    )
    engine = DecodeEngine(model)
    if autoscale:
        return _bench_fleet_autoscale(
            tpu, engine, params, config, max_slots, n_requests)
    n_requests = n_requests or default_requests

    # The bench_serve seeded Poisson trace, shared by every fleet size.
    gaps = rng.exponential(mean_gap_s, n_requests)
    arrivals = np.cumsum(gaps)
    requests = [
        (
            float(arrivals[i]),
            rng.randint(0, config.vocab_size,
                        rng.choice(prompt_lens)).tolist(),
            int(rng.randint(*max_new_range)),
        )
        for i in range(n_requests)
    ]
    total_tokens = sum(m for _, _, m in requests)

    def stream_through_router(port, offset, prompt, max_new, t0, out):
        import http.client
        import json as json_lib

        lag = t0 + offset - time.perf_counter()
        if lag > 0:
            time.sleep(lag)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=600)
        try:
            conn.request(
                "POST", "/v1/generate",
                json_lib.dumps({"prompt": prompt,
                                "max_new_tokens": max_new,
                                "stream": True}),
                {"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            first = None
            n_tokens = 0
            # Read to EOF (not just the done line): draining the
            # terminal chunk means the router has finished its own
            # accounting for this request before we count it done.
            while True:
                line = resp.readline()
                if not line:
                    break
                payload = json_lib.loads(line)
                if "token" in payload:
                    if first is None:
                        first = time.perf_counter()
                    n_tokens += 1
            out.append({
                "status": resp.status,
                "n_tokens": n_tokens,
                "ttft_s": (first - (t0 + offset))
                if first is not None else None,
            })
        finally:
            conn.close()

    def run_fleet(n_replicas):
        # Reset the process registry so this row's fleet-merged sketch
        # (the in-process replicas share one registry) only holds this
        # row's observations.
        telemetry.get_registry().clear()
        kv = InProcessKV()
        replicas = []
        for index in range(n_replicas):
            scheduler = SlotScheduler(
                engine, params, max_slots=max_slots,
                queue_capacity=n_requests,
            )
            scheduler.start()
            server = ServingServer(scheduler, "127.0.0.1", 0)
            server.start()
            task = f"serving:{index}"
            event.serving_endpoint_event(kv, task, server.endpoint)
            replicas.append((task, scheduler, server))
        registry = ReplicaRegistry(
            kv, tasks=[task for task, _, _ in replicas],
            probe_interval_s=0.2,
        )
        registry.refresh(force=True)
        monitor = FleetMonitor(registry, interval_s=0.2)
        router = RouterServer(
            registry, make_policy("least_loaded"), "127.0.0.1", 0,
            retries=2, monitor=monitor,
        )
        router.start()
        monitor.start()
        try:
            # Warmup compiles every prompt bucket's prefill + the step
            # program outside the timed window (shared engine: paid
            # once across the whole sweep).
            for length in prompt_lens:
                replicas[0][1].submit(
                    [1] * length, SamplingParams(max_new_tokens=2)
                ).result(timeout=600)
            results = []
            threads = []
            t0 = time.perf_counter()
            for offset, prompt, max_new in requests:
                thread = threading.Thread(
                    target=stream_through_router,
                    args=(router.port, offset, prompt, max_new, t0,
                          results),
                )
                thread.start()
                threads.append(thread)
            for thread in threads:
                thread.join(timeout=900)
            wall = time.perf_counter() - t0
            completed = [r for r in results if r["status"] == 200]
            ttfts = sorted(
                r["ttft_s"] for r in completed if r["ttft_s"] is not None
            )
            generated = sum(r["n_tokens"] for r in completed)
            row = {
                "replicas": n_replicas,
                "completed": len(completed),
                "tokens_per_sec": round(generated / wall, 2),
                "wall_s": round(wall, 3),
            }
            if ttfts:
                row["ttft_mean_ms"] = round(
                    1000 * sum(ttfts) / len(ttfts), 2
                )
                row["ttft_p95_ms"] = round(
                    1000 * ttfts[int(0.95 * (len(ttfts) - 1))], 2
                )
            router_stats = router.stats()
            row["healthy_replicas"] = router_stats["healthy_replicas"]
            row["routed_ok"] = sum(
                outcomes.get("ok", 0)
                for outcomes in router_stats["routed_requests"].values()
            )
            # The fleet observability plane's own numbers: the
            # scrape-merged fleet TTFT p95 (server-side, pooled over
            # every replica's sketch — what the autoscaler sees, vs
            # the client-side ttft_p95_ms above which includes the
            # router hop) and the scrape overhead per monitor cycle.
            aggregate = monitor.poll_once()
            if aggregate.get("status") == "ok":
                fleet_ttft = aggregate["histograms"].get(
                    "serving/ttft_seconds", {})
                if "p95" in fleet_ttft:
                    row["fleet_ttft_p95_ms"] = round(
                        1000 * fleet_ttft["p95"], 2)
                row["monitor_cycles"] = aggregate["cycle"]
                row["monitor_scrape_wall_ms"] = round(
                    1000 * aggregate["scrape_wall_s"], 3)
            return row
        finally:
            monitor.stop()
            router.stop()
            for _task, scheduler, server in replicas:
                server.stop()
                scheduler.close()

    rows = {}
    for count in replica_counts:
        try:
            rows[f"r{count}"] = run_fleet(count)
        except Exception as exc:  # noqa: BLE001 - record, keep benching
            rows[f"r{count}"] = {"error": f"{type(exc).__name__}: {exc}"[:160]}
    result = {
        "requests": n_requests,
        "max_slots": max_slots,
        "total_max_new_tokens": total_tokens,
        "rows": rows,
    }
    base = rows.get(f"r{replica_counts[0]}", {}).get("tokens_per_sec")
    for count in replica_counts[1:]:
        top = rows.get(f"r{count}", {}).get("tokens_per_sec")
        if base and top:
            result[f"scaling_r{count}_vs_r{replica_counts[0]}"] = round(
                top / base, 3
            )
    return result


def _bench_fleet_autoscale(tpu, engine, params, config, max_slots,
                           n_requests=None):
    """`fleet --autoscale`: the elastic A/B (see bench_fleet's
    docstring). Static 2-replica arm vs autoscaled arm (start 2, max 4)
    under one seeded rate-step Poisson trace with one injected replica
    preemption + relaunch-on-a-new-port in both arms."""
    import sys
    import threading
    import time

    import numpy as np

    from tf_yarn_tpu import event, telemetry
    from tf_yarn_tpu.coordination.kv import InProcessKV
    from tf_yarn_tpu.fleet import (
        AutoscalePolicy,
        FleetAutoscaler,
        FleetMonitor,
        ReplicaRegistry,
        RouterServer,
        make_policy,
    )
    from tf_yarn_tpu.serving import SamplingParams, ServingServer, SlotScheduler

    rng = np.random.RandomState(7)
    if tpu:
        n_requests = n_requests or 48
        mean_gap_s, step_factor = 0.05, 4.0
        block_size, prefix_len = 16, 64
        tail_lens, max_new_range = (32, 64, 96), (16, 96)
        slo_ttft_s, interval_s = 0.5, 0.1
        ab_slots = max_slots
    else:
        n_requests = n_requests or 24
        mean_gap_s, step_factor = 0.04, 4.0
        block_size, prefix_len = 8, 16
        tail_lens, max_new_range = (3, 5, 8), (2, 10)
        slo_ttft_s, interval_s = 0.5, 0.05
        # Few slots per replica so TTFT is queue-wait dominated: extra
        # replicas add admission capacity even on a GIL-shared CPU rig.
        ab_slots = min(4, max_slots)

    # ONE seeded trace for both arms: Poisson at the base rate for the
    # first half, then the gaps compress by step_factor (the demand
    # surge the autoscaled arm should absorb). Every prompt opens with
    # a shared prefix so the prefix cache — and the peer warm start
    # that ships it — has something to hit.
    gaps = rng.exponential(mean_gap_s, n_requests)
    gaps[n_requests // 2:] /= step_factor
    arrivals = np.cumsum(gaps)
    shared_prefix = rng.randint(0, config.vocab_size, prefix_len).tolist()
    requests = [
        (
            float(arrivals[i]),
            shared_prefix + rng.randint(
                0, config.vocab_size, rng.choice(tail_lens)).tolist(),
            int(rng.randint(*max_new_range)),
        )
        for i in range(n_requests)
    ]
    kill_at = float(arrivals[n_requests // 3])

    def stream_ab(port, offset, prompt, max_new, t0, out, index):
        import http.client
        import json as json_lib

        lag = t0 + offset - time.perf_counter()
        if lag > 0:
            time.sleep(lag)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=600)
        try:
            conn.request(
                "POST", "/v1/generate",
                json_lib.dumps({"prompt": prompt,
                                "max_new_tokens": max_new,
                                "stream": True}),
                {"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            first = None
            tokens = []
            dropped = resp.status != 200
            while True:
                line = resp.readline()
                if not line:
                    break
                payload = json_lib.loads(line)
                if "token" in payload:
                    if first is None:
                        first = time.perf_counter()
                    tokens.append(int(payload["token"]))
                if payload.get("error"):
                    dropped = True
            out.append({
                "index": index,
                "status": resp.status,
                "tokens": tokens,
                "dropped": dropped,
                "ttft_s": (first - (t0 + offset))
                if first is not None else None,
            })
        except Exception as exc:  # noqa: BLE001 - record, keep benching
            out.append({"index": index, "status": 0, "tokens": [],
                        "dropped": True, "ttft_s": None,
                        "error": f"{type(exc).__name__}: {exc}"[:120]})
        finally:
            conn.close()

    def run_arm(autoscaled):
        telemetry.get_registry().clear()
        kv = InProcessKV()
        state_lock = threading.Lock()
        replicas = []
        next_id = [0]

        def spawn_replica(task=None):
            with state_lock:
                if task is None:
                    task = f"serving:{next_id[0]}"
                next_id[0] = max(next_id[0],
                                 int(task.split(":", 1)[1]) + 1)
            scheduler = SlotScheduler(
                engine, params, max_slots=ab_slots,
                queue_capacity=max(64, n_requests),
                kv_layout="paged", block_size=block_size,
                prefix_cache_capacity=64,
            )
            scheduler.start()
            server = ServingServer(scheduler, "127.0.0.1", 0)
            server.start()
            # Advertise AFTER the server listens: the registry probes
            # the advertised address on its next refresh pass.
            event.serving_endpoint_event(kv, task, server.endpoint)
            with state_lock:
                replicas.append((task, scheduler, server))
            return task

        for _ in range(2):
            spawn_replica()
        registry = ReplicaRegistry(
            kv, tasks=None, probe_interval_s=interval_s / 2,
        )
        registry.refresh(force=True)
        monitor = FleetMonitor(registry, interval_s=interval_s)
        autoscaler = None
        if autoscaled:
            def actuate(kind, current, target, reason):
                if kind != "generate" or target <= current:
                    return False
                # Idempotent against the registry's lag: `current` is
                # the fleet the registry can SEE, which trails replicas
                # still constructing — spawn toward the target from the
                # count of distinct tasks ever launched, not by delta.
                with state_lock:
                    missing = target - next_id[0]
                if missing <= 0:
                    return False
                # Launch off-thread: real relaunches take seconds and
                # the decision loop must not block on them.
                threading.Thread(
                    target=lambda: [spawn_replica()
                                    for _ in range(missing)],
                    name="bench-scale-out", daemon=True,
                ).start()
                return True

            autoscaler = FleetAutoscaler(
                registry, monitor,
                {"generate": AutoscalePolicy(
                    min_replicas=2, max_replicas=4,
                    scale_out_queue_depth=0.5,
                    scale_out_p95_s=slo_ttft_s,
                    scale_in_load=None, cooldown_cycles=2,
                )},
                actuate=actuate, interval_s=interval_s,
            )
        router = RouterServer(
            registry, make_policy("least_loaded"), "127.0.0.1", 0,
            retries=2, monitor=monitor, autoscaler=autoscaler,
        )
        router.start()
        monitor.start()
        stop = threading.Event()

        def refresh_loop():
            while not stop.is_set():
                registry.refresh()
                stop.wait(interval_s / 2)

        refresher = threading.Thread(
            target=refresh_loop, name="bench-registry-refresh",
            daemon=True,
        )
        refresher.start()
        try:
            # Compile every prompt bucket outside the timed window
            # (shared engine: paid once across both arms).
            for tail in tail_lens:
                replicas[0][1].submit(
                    shared_prefix + [1] * tail,
                    SamplingParams(max_new_tokens=2),
                ).result(timeout=600)
            results = []
            threads = []
            t0 = time.perf_counter()

            def chaos_kill():
                lag = t0 + kill_at - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                with state_lock:
                    victim = replicas[0][0]
                registry.report_failure(
                    victim, ConnectionError("preempted (bench chaos)"),
                )
                # Relaunch under the SAME task name on a NEW port: the
                # registry re-admit path probes the fresh address, and
                # the autoscaled arm warm-starts the cold cache from a
                # live peer over /v1/blocks. In-flight streams on the
                # old server drain to completion (zero dropped).
                spawn_replica(task=victim)

            killer = threading.Thread(
                target=chaos_kill, name="bench-chaos", daemon=True,
            )
            killer.start()
            for index, (offset, prompt, max_new) in enumerate(requests):
                thread = threading.Thread(
                    target=stream_ab,
                    args=(router.port, offset, prompt, max_new, t0,
                          results, index),
                )
                thread.start()
                threads.append(thread)
            # The main thread paces the autoscaler for the trace's
            # duration: production runs autoscaler.start()'s side-car
            # thread, but under a saturated bench GIL a side-car gets
            # starved to a couple of cycles — polling from the load
            # generator's clock keeps the decision cadence honest in
            # both arms' measurement windows.
            deadline = time.perf_counter() + 900
            while any(t.is_alive() for t in threads):
                if time.perf_counter() > deadline:
                    break
                if autoscaler is not None:
                    try:
                        autoscaler.poll_once()
                    except Exception:  # noqa: BLE001 - cycle, not arm
                        pass
                time.sleep(interval_s)
            for thread in threads:
                thread.join(timeout=60)
            killer.join(timeout=60)
            # Ingest any relaunch/scale-out that advertised after the
            # refresher's last pass, then run a final decision cycle: a
            # re-admission that landed after the last in-trace poll
            # still warm-starts (the endpoint-change trigger is
            # stateful, not edge-sampled).
            registry.refresh(force=True)
            if autoscaler is not None:
                autoscaler.poll_once()
            wall = time.perf_counter() - t0
            violated = sum(
                1 for r in results
                if r["dropped"] or r["ttft_s"] is None
                or r["ttft_s"] > slo_ttft_s
            )
            ttfts = sorted(
                r["ttft_s"] for r in results if r["ttft_s"] is not None
            )
            row = {
                "completed": sum(1 for r in results if not r["dropped"]),
                "dropped": sum(1 for r in results if r["dropped"]),
                "wall_s": round(wall, 3),
                "slo_violation_rate": round(
                    violated / max(1, len(results)), 3),
            }
            if ttfts:
                row["ttft_p95_ms"] = round(
                    1000 * ttfts[int(0.95 * (len(ttfts) - 1))], 2)
            snapshot = registry.snapshot()
            row["replicas_final"] = snapshot["healthy_replicas"]
            row["readmissions"] = snapshot["readmissions_total"]
            if autoscaler is not None:
                stats = autoscaler.stats()
                row["autoscaler_cycles"] = stats["cycles"]
                row["scale_events"] = len(stats["scale_events"])
                # pulls = attempts; warm_starts = pulls that shipped
                # blocks (a pull that finds the peer already re-heated
                # organically imports 0 — the fleet healed either way).
                row["warm_start_pulls"] = len(stats["warm_starts"])
                row["warm_starts"] = sum(
                    1 for w in stats["warm_starts"]
                    if w.get("imported_blocks")
                )
                row["warm_start_blocks"] = int(
                    telemetry.get_registry().counter(
                        "fleet/warm_start_blocks_total").value
                )
            streams = {r["index"]: list(r["tokens"]) for r in results}
            return row, streams
        finally:
            stop.set()
            if autoscaler is not None:
                autoscaler.stop()
            monitor.stop()
            router.stop()
            refresher.join(timeout=10)
            with state_lock:
                final = list(replicas)
            for _task, scheduler, server in final:
                server.stop()
                scheduler.close()

    rows = {}
    streams = {}
    switch_interval = sys.getswitchinterval()
    sys.setswitchinterval(0.002)  # fairer GIL handoff under 24+ threads
    try:
        for arm, autoscaled in (("static", False), ("autoscaled", True)):
            try:
                rows[arm], streams[arm] = run_arm(autoscaled)
            except Exception as exc:  # noqa: BLE001 - record, keep benching
                rows[arm] = {"error": f"{type(exc).__name__}: {exc}"[:160]}
    finally:
        sys.setswitchinterval(switch_interval)
    result = {
        "mode": "autoscale_ab",
        "requests": n_requests,
        "max_slots": max_slots,
        "slo_ttft_s": slo_ttft_s,
        "rate_step_factor": step_factor,
        "kill_at_s": round(kill_at, 3),
        "rows": rows,
    }
    if len(streams) == 2:
        # Scaling must change WHEN tokens arrive, never WHICH: the two
        # arms' per-request token sequences must be bit-identical.
        result["streams_match"] = streams["static"] == streams["autoscaled"]
    static_row, auto_row = rows.get("static", {}), rows.get("autoscaled", {})
    if "slo_violation_rate" in static_row \
            and "slo_violation_rate" in auto_row:
        result["violation_delta"] = round(
            static_row["slo_violation_rate"]
            - auto_row["slo_violation_rate"], 3,
        )
    if not tpu:
        result["note"] = (
            "CPU rig: latency rows are scheduling evidence only; the "
            "TPU row is the capacity claim"
        )
    return result


def bench_rank(tpu: bool, waits_ms=(0.0, 2.0, 5.0)):
    """Online-ranking micro-batch bench: ONE seeded Poisson arrival
    trace of feature batches replayed through the fill-or-timeout
    scheduler (tf_yarn_tpu/ranking/) at max_wait_ms ∈ {0, 2, 5} —
    the batching-policy knob's whole trade in three rows. `wait0` is
    tick-on-arrival (best p50, one engine call per request); larger
    waits coalesce rows per compiled forward, buying requests/s with
    queue latency. Every row shares the trace AND the engine, so the
    deltas are policy-only (no recompiles inside the timed window)."""
    import threading
    import time

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_yarn_tpu.models.dlrm import DLRM, DLRMConfig
    from tf_yarn_tpu.models.rank_engine import RankEngine
    from tf_yarn_tpu.parallel.mesh import select_devices
    from tf_yarn_tpu.ranking.scheduler import MicroBatchScheduler

    select_devices()
    if tpu:
        config = DLRMConfig.criteo()
        n_requests, mean_gap_s, row_choices = 256, 0.002, (1, 2, 4, 8)
        max_batch, buckets = 64, (1, 2, 4, 8, 16, 32, 64)
    else:
        config = DLRMConfig.tiny()
        n_requests, mean_gap_s, row_choices = 48, 0.003, (1, 2, 4)
        max_batch, buckets = 8, (1, 2, 4, 8)
    model = DLRM(config)
    rng = np.random.RandomState(0)
    sizes = np.asarray(config.table_sizes)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, len(sizes)), jnp.int32),
        jnp.zeros((1, config.n_dense), jnp.float32),
    ))
    engine = RankEngine(model, batch_buckets=buckets)

    # One seeded Poisson trace shared by every max_wait_ms row.
    arrivals = np.cumsum(rng.exponential(mean_gap_s, n_requests))
    trace = []
    for index in range(n_requests):
        rows = int(rng.choice(row_choices))
        trace.append((
            float(arrivals[index]),
            rng.randint(0, sizes, (rows, len(sizes))).astype(np.int32),
            rng.randn(rows, config.n_dense).astype(np.float32),
        ))
    total_rows = sum(cat.shape[0] for _, cat, _ in trace)

    def run_row(max_wait_ms):
        scheduler = MicroBatchScheduler(
            engine, params, max_batch=max_batch,
            max_wait_ms=max_wait_ms, queue_capacity=n_requests,
        )
        # Warmup compiles every bucket outside the timed window (cache
        # hits from the second row on — the engine is shared).
        engine.warmup(scheduler.params, max_batch=max_batch)
        ticks_before = scheduler.stats()["ticks"]
        scheduler.start()
        try:
            latencies = [None] * n_requests

            def client(index, offset, cat, dense, t0):
                lag = t0 + offset - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                scheduler.submit(cat, dense).result(timeout=600)
                # Measured against the TRACE arrival, so queue wait —
                # the cost max_wait_ms deliberately adds — counts.
                latencies[index] = time.perf_counter() - (t0 + offset)

            threads = []
            t0 = time.perf_counter()
            for index, (offset, cat, dense) in enumerate(trace):
                thread = threading.Thread(
                    target=client, args=(index, offset, cat, dense, t0)
                )
                thread.start()
                threads.append(thread)
            for thread in threads:
                thread.join(timeout=900)
            wall = time.perf_counter() - t0
            done = sorted(lat for lat in latencies if lat is not None)
            stats = scheduler.stats()
            ticks = stats["ticks"] - ticks_before
            return {
                "max_wait_ms": max_wait_ms,
                "completed": len(done),
                "requests_per_sec": round(len(done) / wall, 2),
                "rows_per_sec": round(total_rows / wall, 2),
                "latency_p50_ms": round(
                    1000 * done[len(done) // 2], 2),
                "latency_p95_ms": round(
                    1000 * done[int(0.95 * (len(done) - 1))], 2),
                "ticks": ticks,
                "rows_per_tick": round(total_rows / ticks, 2)
                if ticks else None,
            }
        finally:
            scheduler.close()

    rows = {}
    for wait in waits_ms:
        name = f"wait{wait:g}ms"
        try:
            rows[name] = run_row(wait)
        except Exception as exc:  # noqa: BLE001 - record, keep benching
            rows[name] = {"error": f"{type(exc).__name__}: {exc}"[:160]}
    return {
        "requests": n_requests,
        "total_rows": total_rows,
        "max_batch": max_batch,
        "mean_gap_ms": mean_gap_s * 1000,
        "forward_compiles": engine.stats["forward_compiles"],
        "rows": rows,
        "note": (
            "one shared trace + engine per row: requests/s and p95 vs "
            "max_wait_ms is the fill-or-timeout policy trade, nothing "
            "else"
        ),
    }


def bench_ici_allreduce(tpu: bool):
    from tf_yarn_tpu.parallel.collectives import allreduce_bandwidth
    from tf_yarn_tpu.parallel.mesh import select_devices

    return allreduce_bandwidth(
        size_mb=64.0 if tpu else 2.0, iters=10, devices=select_devices()
    )


def bench_analysis(tpu: bool):
    """Wall seconds per static-analysis engine (ast/jaxpr/hlo/concurrency)
    over the repo's own tree — the checker is a tier-1 gate, so its
    budget is a tracked number, not a vibe. Runs the real CLI in a
    subprocess (the exact gate invocation, import cost included) and
    reports the per-engine breakdown the CLI already times.

    Device-independent: the jaxpr/hlo engines trace tiny shapes and the
    lockset scenarios are pure-Python, so the CPU number IS the claim.
    """
    import subprocess
    import time

    env = dict(os.environ, JAX_PLATFORMS="cpu")  # the gate's environment
    started = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "tf_yarn_tpu.analysis", "tf_yarn_tpu",
         "--json"],
        capture_output=True, text=True, env=env, timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    total_s = time.monotonic() - started
    if proc.returncode != 0:
        # A dirty tree is a finding, not a crash: surface it in-band so
        # the bench line records WHY the seconds are missing.
        return {
            "exit_code": proc.returncode,
            "total_s": total_s,
            "error": (proc.stdout or proc.stderr).strip()[:400],
        }
    payload = json.loads(proc.stdout)
    race = payload.get("race_report") or {}
    return {
        "exit_code": proc.returncode,
        "total_s": total_s,
        **{f"{name}_s": secs
           for name, secs in (payload.get("engine_seconds") or {}).items()},
        "n_findings": payload.get("n_findings"),
        "n_suppressed": len(payload.get("suppressed_findings") or ()),
        "race_scenarios": len(race),
        "race_accesses": sum(
            s.get("accesses", 0) for s in race.values()
        ),
        "note": (
            "per-engine wall seconds for the four-engine checker on "
            "tf_yarn_tpu/ (subprocess = gate-identical, interpreter "
            "startup inside total_s only)"
        ),
    }


CONFIGS = {
    "mnist_dense": bench_mnist_dense,
    "linear_clicks": bench_linear_clicks,
    "bert_base": bench_bert_base,
    "dlrm_clicks": bench_dlrm_clicks,
    "resnet50": bench_resnet50,
    "vit_base": bench_vit_base,
    "llama_lora": bench_llama_lora,
    "long_context": bench_long_context,
    "decode": bench_decode,
    "serve": bench_serve,
    "fleet": bench_fleet,
    "rank": bench_rank,
    "ici_allreduce": bench_ici_allreduce,
    "analysis": bench_analysis,
}


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("configs", nargs="*", default=list(CONFIGS))
    parser.add_argument("--cpu", action="store_true", help="force tiny CPU shapes")
    parser.add_argument(
        "--spec", action="store_true",
        help="decode config: add the exact-vs-speculative (spec_k) A/B",
    )
    parser.add_argument(
        "--tp", action="store_true",
        help="serve config: add the tp=1 vs tp=2 tensor-parallel A/B",
    )
    parser.add_argument(
        "--chunked", action="store_true",
        help=(
            "serve config: add the blocking-vs-chunked admission "
            "prefill A/B (bimodal trace, TTFT + inter-token-latency p95)"
        ),
    )
    parser.add_argument(
        "--autoscale", action="store_true",
        help=(
            "fleet config: run the static-vs-autoscaled elastic A/B "
            "(rate-step trace + injected replica preemption, "
            "SLO-violation rate + streams_match) instead of the "
            "replica sweep"
        ),
    )
    parser.add_argument(
        "--overload", action="store_true",
        help=(
            "serve config: add the hold-until-free vs suspend-to-host "
            "KV oversubscription A/B (seeded overload trace, peak "
            "streams + interactive TTFT p95 + swap counters)"
        ),
    )
    parser.add_argument(
        "--disagg", action="store_true",
        help=(
            "serve config: add the local vs disaggregated prefill A/B "
            "(bimodal trace through a real prefill replica over HTTP; "
            "TTFT p95, streams_match_local, fp-vs-int8 wire bytes)"
        ),
    )
    args = parser.parse_args()
    if args.cpu:
        os.environ["TPU_YARN_PLATFORM"] = "cpu"  # explicit flag wins over env
    if args.tp:
        # The tp A/B needs >= 2 devices; on a CPU rig that means forcing
        # virtual host-platform devices BEFORE jax initializes
        # (parallel.mesh.select_devices reads this env and appends the
        # XLA flag). A real slice already has its chips; the setdefault
        # is harmless there.
        os.environ.setdefault("TPU_YARN_VIRTUAL_DEVICES", "4")
    unknown = [name for name in args.configs if name not in CONFIGS]
    if unknown:
        parser.error(
            f"unknown config(s) {unknown}; choose from {sorted(CONFIGS)}"
        )
    tpu = (not args.cpu) and _on_tpu()
    for name in args.configs:
        if name == "decode":
            result = CONFIGS[name](tpu, spec=args.spec)
        elif name == "serve":
            result = CONFIGS[name](
                tpu, tp=args.tp, chunked=args.chunked,
                overload=args.overload, disagg=args.disagg,
            )
        elif name == "fleet":
            result = CONFIGS[name](tpu, autoscale=args.autoscale)
        else:
            result = CONFIGS[name](tpu)
        print(json.dumps({"config": name, "tpu": tpu, **{
            k: round(v, 4) if isinstance(v, float) else v
            for k, v in result.items()
        }}), flush=True)


if __name__ == "__main__":
    main()
