"""Batch-size / knob sweep for the flagship decoder on one chip.

Complements benchmarks/run.py (fixed configs) by sweeping the axes that
set single-chip MFU: batch size, remat, scan_layers. One JSON line per
point, so the winner can be promoted into bench.py's headline config.

    python benchmarks/sweep.py --batches 8,16,32
    python benchmarks/sweep.py --batches 4,8 --seq 2048 --remat
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--batches", default="8,16,32")
    parser.add_argument("--seq", type=int, default=1024)
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--d-model", type=int, default=1024)
    parser.add_argument("--n-layers", type=int, default=8)
    parser.add_argument("--remat", action="store_true")
    parser.add_argument("--attention", default="flash", choices=["flash", "xla"])
    parser.add_argument("--scan-layers", action="store_true")
    args = parser.parse_args()

    import numpy as np
    import optax

    from tf_yarn_tpu.benchmark import measure_throughput
    from tf_yarn_tpu.models import common
    from tf_yarn_tpu.models.transformer import Transformer, TransformerConfig

    config = TransformerConfig(
        vocab_size=32000,
        d_model=args.d_model,
        n_layers=args.n_layers,
        n_heads=16,
        n_kv_heads=8,
        d_ff=4 * args.d_model,
        max_seq_len=max(2048, args.seq),
        remat=args.remat,
        attention_impl=args.attention,
        fused_norms=True,
        scan_layers=args.scan_layers,
    )
    model = Transformer(config)
    for batch in [int(b) for b in args.batches.split(",")]:
        tokens = np.random.RandomState(0).randint(
            0, config.vocab_size, (batch, args.seq), dtype=np.int32
        )
        t0 = time.time()
        try:
            stats = measure_throughput(
                model, common.lm_loss, optax.adamw(1e-4),
                {"tokens": tokens}, steps=args.steps,
            )
        except Exception as exc:  # OOM etc. — keep sweeping
            print(json.dumps({"batch": batch, "seq": args.seq,
                              "error": f"{type(exc).__name__}: {exc}"[:200]}),
                  flush=True)
            continue
        print(json.dumps({
            "batch": batch,
            "seq": args.seq,
            "samples_per_sec_per_chip": round(stats["samples_per_sec_per_chip"], 2),
            "step_time_ms": round(stats["step_time_ms"], 2),
            "mfu": round(stats.get("mfu", 0.0), 4),
            "wall_s": round(time.time() - t0, 1),
        }), flush=True)


if __name__ == "__main__":
    main()
